"""Benchmark: simulation rounds/sec at 100 nodes, ours (TPU) vs reference (CPU).

North-star metric from BASELINE.json: "sim rounds/sec at 100 nodes". The
reference publishes no numbers (BASELINE.md), so the baseline is MEASURED
live: the same configuration — 100 nodes, spambase-shaped data (4601x57),
LogisticRegression trained with SGD (CrossEntropy, lr 0.1, 1 local epoch,
batch 32), MERGE_UPDATE, PUSH gossip over a 20-regular graph, per-round
evaluation on the global eval set — is run through the reference's
``GossipSimulator`` (imported from /root/reference, pure PyTorch CPU) and
through gossipy_tpu's jitted engine, and the steady-state rounds/sec are
compared.

Prints ONE JSON line:
    {"metric": "sim_rounds_per_sec_100nodes", "value": <ours>,
     "unit": "rounds/s", "vs_baseline": <ours / reference>}
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import time
import warnings

import numpy as np

N_NODES = 100
ROUND_LEN = 100
# Steady-state measurement: enough rounds per executable call to amortize
# the backend's fixed per-execution dispatch overhead (~65+ ms on the
# tunneled single-chip runtime — at 50 rounds/call that overhead alone
# capped the measurement at ~130 r/s; the program itself runs ~1.2 ms/round).
BENCH_ROUNDS = 2000
BASELINE_ROUNDS = 3
DEGREE = 20
# Reference rounds/s measured on this container's CPU (fallback when the
# live baseline run fails for environmental reasons). Measured 2026-07-29:
# 3 rounds in 2.62s = 1.14 r/s.
FALLBACK_BASELINE = 1.14


def make_data():
    """Deterministic spambase-shaped dataset (4601 x 57, binary)."""
    from gossipy_tpu.data import load_classification_dataset
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        X, y = load_classification_dataset("spambase")
    return X, y


def build_sim(X, y, fused: bool = False):
    """The bench configuration (shared by the throughput and to-accuracy
    modes): 100 nodes, LogReg SGD, MERGE_UPDATE, PUSH over a 20-regular
    graph, per-round global eval."""
    import optax

    from gossipy_tpu.core import AntiEntropyProtocol, CreateModelMode, Topology
    from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
    from gossipy_tpu.handlers import SGDHandler, losses
    from gossipy_tpu.models import LogisticRegression
    from gossipy_tpu.simulation import GossipSimulator

    dh = ClassificationDataHandler(X, y, test_size=0.2, seed=42)
    disp = DataDispatcher(dh, n=N_NODES, eval_on_user=False)
    handler = SGDHandler(model=LogisticRegression(X.shape[1], 2),
                         loss=losses.cross_entropy, optimizer=optax.sgd(0.1),
                         local_epochs=1, batch_size=32, n_classes=2,
                         input_shape=(X.shape[1],),
                         create_model_mode=CreateModelMode.MERGE_UPDATE)
    return GossipSimulator(handler,
                           Topology.random_regular(N_NODES, DEGREE, seed=42),
                           disp.stacked(), delta=ROUND_LEN,
                           protocol=AntiEntropyProtocol.PUSH,
                           fused_merge=fused)


def bench_ours(X, y) -> float:
    import jax

    def run(fused: bool) -> tuple[float, float]:
        sim = build_sim(X, y, fused)
        key = jax.random.PRNGKey(42)
        state = sim.init_nodes(key)
        # Warmup: trigger compilation of the scan.
        s2, _ = sim.start(state, n_rounds=BENCH_ROUNDS, key=key)
        jax.block_until_ready(s2.model.params)
        t0 = time.perf_counter()
        s3, report = sim.start(state, n_rounds=BENCH_ROUNDS, key=key)
        jax.block_until_ready(s3.model.params)
        elapsed = time.perf_counter() - t0
        return elapsed, report.curves(local=False)["accuracy"][-1]

    elapsed, acc = run(False)
    label = "plain"
    try:  # pallas fused deliver path: keep whichever is faster on this chip
        elapsed_f, acc_f = run(True)
        print(f"[bench] fused: {BENCH_ROUNDS} rounds in {elapsed_f:.2f}s",
              file=sys.stderr)
        if elapsed_f < elapsed:
            elapsed, acc, label = elapsed_f, acc_f, "fused"
    except Exception as e:  # kernel unavailable on this backend
        print(f"[bench] fused path unavailable ({e!r})", file=sys.stderr)
    print(f"[bench] ours ({label}): {BENCH_ROUNDS} rounds in {elapsed:.2f}s "
          f"({BENCH_ROUNDS/elapsed:.1f} r/s), final global acc {acc:.3f}",
          file=sys.stderr)
    return BENCH_ROUNDS / elapsed


def bench_reference(X, y) -> float:
    """Run the reference simulator (pure Python/torch) on the same config."""
    sys.path.insert(0, "/root/reference")
    # The reference's data module imports torchvision at top level purely for
    # its CIFAR/FashionMNIST download helpers; stub it (absent in this image).
    import types
    if "torchvision" not in sys.modules:
        tv = types.ModuleType("torchvision")
        tv.datasets = types.ModuleType("torchvision.datasets")
        tv.transforms = types.ModuleType("torchvision.transforms")
        sys.modules["torchvision"] = tv
        sys.modules["torchvision.datasets"] = tv.datasets
        sys.modules["torchvision.transforms"] = tv.transforms
    import torch
    from gossipy import set_seed
    from gossipy.core import AntiEntropyProtocol, ConstantDelay, CreateModelMode, \
        StaticP2PNetwork
    from gossipy.data import DataDispatcher as RefDispatcher
    from gossipy.data.handler import ClassificationDataHandler as RefHandler
    from gossipy.model.handler import TorchModelHandler
    from gossipy.model.nn import LogisticRegression as RefLogReg
    from gossipy.node import GossipNode
    from gossipy.simul import GossipSimulator as RefSimulator, SimulationReport
    import networkx as nx

    # Newer sklearn returns a plain float from roc_auc_score; the reference
    # calls .astype on it (handler.py:328). Shim to numpy scalar.
    import gossipy.model.handler as ref_handler_mod
    _orig_auc = ref_handler_mod.roc_auc_score
    ref_handler_mod.roc_auc_score = lambda *a, **k: np.float64(_orig_auc(*a, **k))

    set_seed(42)
    Xt = torch.tensor(X, dtype=torch.float32)
    yt = torch.tensor(y, dtype=torch.long)
    handler = RefHandler(Xt, yt, test_size=0.2)
    dispatcher = RefDispatcher(handler, n=N_NODES, eval_on_user=False)
    topology = nx.to_numpy_array(
        nx.random_regular_graph(DEGREE, N_NODES, seed=42))
    proto = TorchModelHandler(
        net=RefLogReg(X.shape[1], 2), optimizer=torch.optim.SGD,
        optimizer_params={"lr": 0.1}, criterion=torch.nn.CrossEntropyLoss(),
        local_epochs=1, batch_size=32,
        create_model_mode=CreateModelMode.MERGE_UPDATE)
    nodes = GossipNode.generate(data_dispatcher=dispatcher,
                                p2p_net=StaticP2PNetwork(N_NODES, topology),
                                model_proto=proto, round_len=ROUND_LEN, sync=True)
    simulator = RefSimulator(nodes=nodes, data_dispatcher=dispatcher,
                             delta=ROUND_LEN,
                             protocol=AntiEntropyProtocol.PUSH,
                             delay=ConstantDelay(0),
                             online_prob=1.0, drop_prob=0.0, sampling_eval=0.0)
    report = SimulationReport()
    simulator.add_receiver(report)
    simulator.init_nodes(seed=42)
    t0 = time.perf_counter()
    with contextlib.redirect_stdout(sys.stderr):
        simulator.start(n_rounds=BASELINE_ROUNDS)
    elapsed = time.perf_counter() - t0
    print(f"[bench] reference: {BASELINE_ROUNDS} rounds in {elapsed:.2f}s "
          f"({BASELINE_ROUNDS/elapsed:.2f} r/s)", file=sys.stderr)
    return BASELINE_ROUNDS / elapsed


def bench_to_accuracy(X, y, target: float) -> None:
    """Secondary north-star: wall-clock for OUR side to reach ``target``
    global test accuracy (BASELINE.json "wall-clock to target test-acc") on
    the bench config. The reference comparison point is derived from its
    measured rounds/s (see BASELINE.md) rather than run here — at ~1 round/s
    a live reference run of this mode would take minutes per invocation.
    Not part of the driver's one-line contract; run with
    ``python bench.py --to-acc 0.9``."""
    import jax

    sim = build_sim(X, y)
    key = jax.random.PRNGKey(42)
    chunk = 20
    state = sim.init_nodes(key)
    s_warm, _ = sim.start(state, n_rounds=chunk, key=key)  # compile
    jax.block_until_ready(s_warm.model.params)

    state = sim.init_nodes(key)
    t0 = time.perf_counter()
    rounds_done, hit_at = 0, None
    while rounds_done < 400 and hit_at is None:
        state, report = sim.start(state, n_rounds=chunk, key=key)
        accs = report.curves(local=False)["accuracy"]
        for i, a in enumerate(accs):
            if a >= target:
                hit_at = rounds_done + i + 1
                break
        rounds_done += chunk
    elapsed = time.perf_counter() - t0
    if hit_at is None:
        print(f"[to-acc] ours: target {target} NOT reached in "
              f"{rounds_done} rounds ({elapsed:.2f}s)")
    else:
        print(f"[to-acc] ours: target {target} reached at round {hit_at} "
              f"in {elapsed:.2f}s wall")


def main():
    from gossipy_tpu import enable_compilation_cache
    enable_compilation_cache()
    X, y = make_data()
    if "--to-acc" in sys.argv:
        try:
            target = float(sys.argv[sys.argv.index("--to-acc") + 1])
        except (IndexError, ValueError):
            sys.exit("usage: python bench.py --to-acc <target accuracy in "
                     "(0, 1]>, e.g. --to-acc 0.95")
        bench_to_accuracy(X, y, target)
        return
    ours = bench_ours(X, y)
    try:
        baseline = bench_reference(X, y)
    except Exception as e:  # environmental failure only
        print(f"[bench] reference baseline failed ({e!r}); "
              f"using fallback {FALLBACK_BASELINE} r/s", file=sys.stderr)
        baseline = FALLBACK_BASELINE
    print(json.dumps({
        "metric": "sim_rounds_per_sec_100nodes",
        "value": round(ours, 2),
        "unit": "rounds/s",
        "vs_baseline": round(ours / baseline, 2),
    }))


if __name__ == "__main__":
    main()
