"""Benchmark: simulation rounds/sec at 100 nodes, ours (TPU) vs reference (CPU).

North-star metric from BASELINE.json: "sim rounds/sec at 100 nodes". The
reference publishes no numbers (BASELINE.md), so the baseline is MEASURED
live: the same configuration — 100 nodes, spambase-shaped data (4601x57),
LogisticRegression trained with SGD (CrossEntropy, lr 0.1, 1 local epoch,
batch 32), MERGE_UPDATE, PUSH gossip over a 20-regular graph, per-round
evaluation on the global eval set — is run through the reference's
``GossipSimulator`` (imported from /root/reference, pure PyTorch CPU) and
through gossipy_tpu's jitted engine, and the steady-state rounds/sec are
compared.

Prints ONE JSON line:
    {"metric": "sim_rounds_per_sec_100nodes", "value": <ours>,
     "unit": "rounds/s", "vs_baseline": <ours / reference>}
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import time
import warnings

import numpy as np

N_NODES = 100
ROUND_LEN = 100
# Steady-state measurement: enough rounds per executable call to amortize
# the backend's fixed per-execution dispatch overhead (~65+ ms on the
# tunneled single-chip runtime — at 50 rounds/call that overhead alone
# capped the measurement at ~130 r/s; the program itself runs ~1.2 ms/round).
BENCH_ROUNDS = 2000
# When the accelerator is unreachable the bench degrades to a labeled CPU
# run (see main()); the dispatch-overhead rationale above does not apply to
# the in-process CPU backend, so a shorter measurement keeps the outage
# path fast.
BENCH_ROUNDS_DEGRADED = 200
# Set by the --_degraded re-exec: this run is a labeled CPU fallback, not
# an accelerator measurement.
DEGRADED = False
# The reference runs ~1 round/s on this host's CPU; 10 rounds keeps the
# baseline run ~10 s while cutting the 2x noise band a 3-round sample showed
# (VERDICT round 1). The JSON line carries both raw rates so the speedup
# quote has a checkable denominator.
BASELINE_ROUNDS = 10
DEGREE = 20
# Reference rounds/s measured on this container's CPU (fallback when the
# live baseline run fails for environmental reasons). Measured 2026-07-29:
# FALLBACK_BASELINE_ROUNDS rounds in 2.62s = 1.14 r/s.
FALLBACK_BASELINE = 1.14
FALLBACK_BASELINE_ROUNDS = 3
# Wire/storage format of the params-history ring (--history-dtype flag):
# float32 (exact), bfloat16, int8 — see GossipSimulator(history_dtype=...).
HISTORY_DTYPE = "float32"
# Wire-traffic stamp filled by the measured run (bytes moved per round under
# the configured format), merged into the emitted JSON's raw block.
WIRE_INFO: dict = {}
# Probes-on vs probes-off throughput stamp (north-star mode): the overhead
# of the opt-in gossip-dynamics probes, itself observed. Merged into raw.
PROBE_INFO: dict = {}
# Sentinels-on vs sentinels-off throughput stamp (north-star mode): the
# overhead of the opt-in numerics sentinels (telemetry.health; ISSUE-4
# acceptance target < 5% on this config). Merged into raw.
SENTINEL_INFO: dict = {}
# Chaos-on vs chaos-off throughput stamp (north-star mode): the overhead
# of the opt-in scheduled fault-injection layer (simulation.faults;
# ISSUE-7 acceptance target < 5% like sentinels) under a representative
# scenario — a half/half partition plus a drop spike inside the measured
# window. Merged into raw.
CHAOS_INFO: dict = {}
# Performance-observability stamp (telemetry.cost; the measured run now
# carries perf=True): XLA's per-round FLOP count, the program's HBM peak
# from memory_analysis(), and the measured-wall-time MFU estimate (null
# off known accelerators). Merged into raw — EVERY bench row carries the
# trio so a TPU window banks its on-chip evidence automatically.
PERF_INFO: dict = {}
# Tracing-on vs tracing-off throughput stamp (north-star mode): the
# overhead of the opt-in host span tracer (telemetry.tracing; ISSUE-16
# acceptance target < 2% — host-side only, one extra block_until_ready
# per start), plus the traced run's critical-path account
# (host_blocked_frac / overlap_frac from trace_report). Merged into raw.
TRACING_INFO: dict = {}


def emit(payload: dict) -> None:
    """Print the one-line JSON contract, stamped with the backend actually
    used and whether this run is the degraded CPU fallback."""
    import jax
    raw = payload.setdefault("raw", {})
    raw.setdefault("backend", jax.default_backend())
    raw.setdefault("device_kind", jax.devices()[0].device_kind)
    try:
        # Synthetic-data generation version: accuracy-bearing rows from
        # different generator recipes must not be compared as one regime
        # (the throughput metrics don't care, the to-accuracy ones do).
        from gossipy_tpu.data import SYNTHETIC_DATA_VERSION
        raw.setdefault("data_version", SYNTHETIC_DATA_VERSION)
    except Exception:
        pass
    raw["degraded"] = DEGRADED
    if DEGRADED and os.environ.get("GOSSIPY_TPU_DEGRADE_REASON"):
        raw["degrade_reason"] = os.environ["GOSSIPY_TPU_DEGRADE_REASON"]
    if raw["backend"] == "cpu" and not DEGRADED:
        # The liveness probe only proves jax INITIALIZES — an accelerator
        # plugin that silently falls back (or a plugin-free environment)
        # reaches here on the CPU backend without having tripped the
        # degrade path. A CPU row must never reach the driver unlabeled.
        raw["degraded"] = True
        raw.setdefault("degrade_reason",
                       "backend initialized as cpu (accelerator absent or "
                       "plugin fell back)")
    print(json.dumps(payload))
    try:
        # Run-ledger ingest (telemetry.ledger; opt-in via the
        # GOSSIPY_TPU_LEDGER env var): every emitted row also lands as a
        # digest row in the process's run index. Best-effort — the
        # stdout one-line contract above is the measurement of record.
        from gossipy_tpu.telemetry.ledger import (ingest_bench_capsule,
                                                  resolve_ledger)
        led = resolve_ledger(None)
        if led is not None:
            ingest_bench_capsule(led, payload)
    except Exception as e:
        print(f"[ledger] ingest failed: {e!r}", file=sys.stderr)


def emit_manifest(sim, mode: str) -> None:
    """Emit the run's RunManifest JSON: one ``[manifest] {...}`` line on
    STDERR (the stdout one-line metric contract is untouched) plus an
    optional file copy at ``$GOSSIPY_TPU_MANIFEST``. Collection is
    best-effort — a manifest failure must never take down a finished
    measurement."""
    try:
        manifest = sim.run_manifest(extra={"bench_mode": mode})
        line = manifest.to_json()
    except Exception as e:
        print(f"[manifest] collection failed: {e!r}", file=sys.stderr)
        return
    print("[manifest] " + line, file=sys.stderr)
    path = os.environ.get("GOSSIPY_TPU_MANIFEST")
    if path:
        try:
            manifest.save(path)
        except OSError as e:
            print(f"[manifest] could not write {path}: {e!r}",
                  file=sys.stderr)


def stamp_wire_traffic(sim, report, rounds: int) -> None:
    """One stderr line + ``WIRE_INFO`` raw fields for the run's wire
    traffic under the configured ``--history-dtype``: bytes one message
    moves and the measured bytes-moved-per-round (sent/round x
    wire_bytes_per_message — the history-ring gather traffic the deliver
    phase actually pays, quantized payload + int8 scale sidecar)."""
    try:
        per_msg = sim.wire_bytes_per_message()
        per_round = report.sent_messages / max(rounds, 1) * per_msg
    except Exception as e:  # a stamp failure must not kill a measurement
        print(f"[bench] wire stamp failed: {e!r}", file=sys.stderr)
        return
    WIRE_INFO.update({
        "history_dtype": sim.history_dtype,
        "wire_bytes_per_message": int(per_msg),
        "wire_bytes_per_round": round(per_round, 1),
    })
    print(f"[bench] wire: history_dtype={sim.history_dtype}, "
          f"{per_msg} B/message, ~{per_round:,.0f} bytes moved/round",
          file=sys.stderr)


def make_data():
    """Deterministic spambase-shaped dataset (4601 x 57, binary)."""
    from gossipy_tpu.data import load_classification_dataset
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        X, y = load_classification_dataset("spambase")
    return X, y


def bench_chaos_config(n_rounds: int):
    """The representative chaos scenario for the A/B stamp: the
    population partitioned in half for the middle third of the measured
    window, plus a short drop spike — edge masks, component probes-free
    schedule gathers and a traced drop rate all exercised."""
    from gossipy_tpu.simulation.faults import ChaosConfig, FaultSpike, \
        PartitionEpisode
    a = max(n_rounds // 3, 1)
    b = max(2 * n_rounds // 3, a + 1)
    half = N_NODES // 2
    return ChaosConfig(
        partitions=(PartitionEpisode(
            components=(tuple(range(half)), tuple(range(half, N_NODES))),
            start=a, stop=b),),
        spikes=(FaultSpike(start=b, stop=b + max(n_rounds // 10, 1),
                           drop_prob=0.2),),
        horizon=n_rounds)


def stamp_perf(sim) -> None:
    """``PERF_INFO`` raw fields from a perf-enabled simulator's
    :meth:`perf_summary` — the uniform ``mfu_est`` / ``flops_per_round``
    / ``hbm_peak_bytes`` trio. Null-safe and best-effort: a stamp
    failure must never kill a finished measurement."""
    try:
        ps = sim.perf_summary()
    except Exception as e:
        print(f"[bench] perf stamp failed: {e!r}", file=sys.stderr)
        return
    if ps is None:
        return
    last = ps.get("last_run") or {}
    mfu = last.get("mfu_est")
    PERF_INFO.update({
        "mfu_est": round(mfu, 4) if mfu is not None else None,
        "flops_per_round": ps.get("flops_per_round_xla"),
        "hbm_peak_bytes": ps.get("hbm_peak_bytes"),
        "analytic_flops_per_round": (ps.get("analytic") or {})
        .get("flops_per_round"),
    })
    print(f"[bench] perf: {PERF_INFO['flops_per_round']} FLOP/round "
          f"(XLA), hbm peak {PERF_INFO['hbm_peak_bytes']} B, "
          f"mfu_est {PERF_INFO['mfu_est']}", file=sys.stderr)


def build_sim(X, y, fused: bool = False, probes: bool = False,
              sentinels: bool = False, chaos=None, perf: bool = False,
              tracing=None):
    """The bench configuration (shared by the throughput and to-accuracy
    modes): 100 nodes, LogReg SGD, MERGE_UPDATE, PUSH over a 20-regular
    graph, per-round global eval."""
    import optax

    from gossipy_tpu.core import AntiEntropyProtocol, CreateModelMode, Topology
    from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
    from gossipy_tpu.handlers import SGDHandler, losses
    from gossipy_tpu.models import LogisticRegression
    from gossipy_tpu.simulation import GossipSimulator

    dh = ClassificationDataHandler(X, y, test_size=0.2, seed=42)
    disp = DataDispatcher(dh, n=N_NODES, eval_on_user=False)
    handler = SGDHandler(model=LogisticRegression(X.shape[1], 2),
                         loss=losses.cross_entropy, optimizer=optax.sgd(0.1),
                         local_epochs=1, batch_size=32, n_classes=2,
                         input_shape=(X.shape[1],),
                         create_model_mode=CreateModelMode.MERGE_UPDATE)
    return GossipSimulator(handler,
                           Topology.random_regular(N_NODES, DEGREE, seed=42,
                                                   backend="networkx"),
                           disp.stacked(), delta=ROUND_LEN,
                           protocol=AntiEntropyProtocol.PUSH,
                           fused_merge=fused,
                           history_dtype=HISTORY_DTYPE,
                           probes=probes,
                           sentinels=sentinels,
                           chaos=chaos,
                           perf=perf,
                           tracing=tracing)


def bench_ours(X, y) -> float:
    import jax

    def run(fused: bool, probes: bool = False, sentinels: bool = False,
            chaos=None, perf: bool = False, tracing=None
            ) -> tuple[float, float, object, object]:
        n_rounds = BENCH_ROUNDS_DEGRADED if DEGRADED else BENCH_ROUNDS
        sim = build_sim(X, y, fused, probes=probes, sentinels=sentinels,
                        chaos=chaos, perf=perf, tracing=tracing)
        key = jax.random.PRNGKey(42)
        state = sim.init_nodes(key)
        # Warmup: trigger compilation of the scan (donate_state=False: the
        # timed run below restarts from the SAME initial state).
        s2, _ = sim.start(state, n_rounds=n_rounds, key=key,
                          donate_state=False)
        jax.block_until_ready(s2.model.params)
        if sim.tracer is not None:
            # Traced A/B: the report should account the TIMED window only,
            # not the compile-heavy warmup.
            sim.tracer.clear()
        t0 = time.perf_counter()
        s3, report = sim.start(state, n_rounds=n_rounds, key=key)
        jax.block_until_ready(s3.model.params)
        elapsed = time.perf_counter() - t0
        return elapsed, report.curves(local=False)["accuracy"][-1], sim, \
            report

    n_rounds = BENCH_ROUNDS_DEGRADED if DEGRADED else BENCH_ROUNDS
    elapsed, acc, sim, report = run(False, perf=True)
    label = "plain"
    if jax.default_backend() == "tpu":
        try:  # pallas fused deliver path: keep whichever is faster on this chip
            elapsed_f, acc_f, sim_f, report_f = run(True, perf=True)
            print(f"[bench] fused: {n_rounds} rounds in {elapsed_f:.2f}s",
                  file=sys.stderr)
            if elapsed_f < elapsed:
                elapsed, acc, label, sim, report = \
                    elapsed_f, acc_f, "fused", sim_f, report_f
        except Exception as e:  # kernel unavailable on this backend
            print(f"[bench] fused path unavailable ({e!r})", file=sys.stderr)
    print(f"[bench] ours ({label}): {n_rounds} rounds in {elapsed:.2f}s "
          f"({n_rounds/elapsed:.1f} r/s), final global acc {acc:.3f}",
          file=sys.stderr)
    try:
        # Observability overhead, itself observed: the same plain config
        # with the gossip-dynamics probes on (consensus + staleness +
        # mixing), A/B'd against the probes-off measurement above. The
        # probes-off run IS the default path (probes=None compiles the
        # identical program), so its delta is structurally zero; the
        # probes-on fraction is the stamped cost of watching the dynamics.
        elapsed_p, _, _, _ = run(False, probes=True)
        PROBE_INFO.update({
            "probes_off_rounds_per_sec": round(n_rounds / elapsed, 2),
            "probes_on_rounds_per_sec": round(n_rounds / elapsed_p, 2),
            "probes_overhead_frac": round(
                max(0.0, 1.0 - elapsed / elapsed_p), 4),
        })
        print(f"[bench] probes on: {n_rounds} rounds in {elapsed_p:.2f}s "
              f"({n_rounds / elapsed_p:.1f} r/s; overhead "
              f"{PROBE_INFO['probes_overhead_frac']:.1%} vs probes off)",
              file=sys.stderr)
    except Exception as e:  # the A/B must not kill the main measurement
        print(f"[bench] probes A/B failed ({e!r})", file=sys.stderr)
    try:
        # Sentinel overhead, measured the same way: the plain config with
        # the numerics sentinels on (non-finite counts + divergence EMA +
        # saturation watermarks), A/B'd against the sentinels-off run
        # above (which IS the default path — sentinels=None compiles the
        # identical program). ISSUE-4 acceptance: < 5% on this config.
        elapsed_s, _, _, _ = run(False, sentinels=True)
        SENTINEL_INFO.update({
            "sentinels_off_rounds_per_sec": round(n_rounds / elapsed, 2),
            "sentinels_on_rounds_per_sec": round(n_rounds / elapsed_s, 2),
            "sentinels_overhead_frac": round(
                max(0.0, 1.0 - elapsed / elapsed_s), 4),
        })
        print(f"[bench] sentinels on: {n_rounds} rounds in {elapsed_s:.2f}s "
              f"({n_rounds / elapsed_s:.1f} r/s; overhead "
              f"{SENTINEL_INFO['sentinels_overhead_frac']:.1%} vs "
              "sentinels off)", file=sys.stderr)
    except Exception as e:  # the A/B must not kill the main measurement
        print(f"[bench] sentinels A/B failed ({e!r})", file=sys.stderr)
    try:
        # Chaos-layer overhead, measured the same way: the plain config
        # with a scheduled partition + drop spike (simulation.faults),
        # A/B'd against the chaos-off run above (which IS the default
        # path — chaos=None compiles the identical program). ISSUE-7
        # acceptance: < 5% on this config.
        elapsed_c, _, _, _ = run(False, chaos=bench_chaos_config(n_rounds))
        CHAOS_INFO.update({
            "chaos_off_rounds_per_sec": round(n_rounds / elapsed, 2),
            "chaos_on_rounds_per_sec": round(n_rounds / elapsed_c, 2),
            "chaos_overhead_frac": round(
                max(0.0, 1.0 - elapsed / elapsed_c), 4),
        })
        print(f"[bench] chaos on: {n_rounds} rounds in {elapsed_c:.2f}s "
              f"({n_rounds / elapsed_c:.1f} r/s; overhead "
              f"{CHAOS_INFO['chaos_overhead_frac']:.1%} vs chaos off)",
              file=sys.stderr)
    except Exception as e:  # the A/B must not kill the main measurement
        print(f"[bench] chaos A/B failed ({e!r})", file=sys.stderr)
    try:
        # Span-tracer overhead, measured the same way: the plain config
        # with the host span tracer on, A/B'd against the tracing-off run
        # above (which IS the default path — tracing=None compiles the
        # identical program; the tracer is host-side only). ISSUE-16
        # acceptance: < 2% on this config. The traced run also yields the
        # critical-path account the row carries (host_blocked_frac).
        from gossipy_tpu.telemetry.tracing import Tracer, trace_report
        tr = Tracer(process_name="bench")
        elapsed_t, _, _, _ = run(False, tracing=tr)
        treport = trace_report(tr.snapshot())
        ttot = treport["totals"]
        TRACING_INFO.update({
            "tracing_off_rounds_per_sec": round(n_rounds / elapsed, 2),
            "tracing_on_rounds_per_sec": round(n_rounds / elapsed_t, 2),
            "tracing_overhead_frac": round(
                max(0.0, 1.0 - elapsed / elapsed_t), 4),
            "host_blocked_frac": ttot["host_blocked_frac"],
            "trace_overlap_frac": ttot["overlap_frac"],
            "trace_host_blocked_ms": ttot["host_blocked_ms"],
        })
        print(f"[bench] tracing on: {n_rounds} rounds in {elapsed_t:.2f}s "
              f"({n_rounds / elapsed_t:.1f} r/s; overhead "
              f"{TRACING_INFO['tracing_overhead_frac']:.1%} vs tracing "
              f"off; host blocked "
              f"{TRACING_INFO['host_blocked_frac']:.1%} of wall)",
              file=sys.stderr)
    except Exception as e:  # the A/B must not kill the main measurement
        print(f"[bench] tracing A/B failed ({e!r})", file=sys.stderr)
    stamp_wire_traffic(sim, report, n_rounds)
    stamp_perf(sim)
    emit_manifest(sim, f"north-star/{label}")
    return n_rounds / elapsed


def bench_reference(X, y) -> float:
    """Run the reference simulator (pure Python/torch) on the same config."""
    sys.path.insert(0, "/root/reference")
    # The reference's data module imports torchvision at top level purely for
    # its CIFAR/FashionMNIST download helpers; stub it (absent in this image).
    import types
    if "torchvision" not in sys.modules:
        tv = types.ModuleType("torchvision")
        tv.datasets = types.ModuleType("torchvision.datasets")
        tv.transforms = types.ModuleType("torchvision.transforms")
        sys.modules["torchvision"] = tv
        sys.modules["torchvision.datasets"] = tv.datasets
        sys.modules["torchvision.transforms"] = tv.transforms
    import torch
    from gossipy import set_seed
    from gossipy.core import AntiEntropyProtocol, ConstantDelay, CreateModelMode, \
        StaticP2PNetwork
    from gossipy.data import DataDispatcher as RefDispatcher
    from gossipy.data.handler import ClassificationDataHandler as RefHandler
    from gossipy.model.handler import TorchModelHandler
    from gossipy.model.nn import LogisticRegression as RefLogReg
    from gossipy.node import GossipNode
    from gossipy.simul import GossipSimulator as RefSimulator, SimulationReport
    import networkx as nx

    # Newer sklearn returns a plain float from roc_auc_score; the reference
    # calls .astype on it (handler.py:328). Shim to numpy scalar.
    import gossipy.model.handler as ref_handler_mod
    _orig_auc = ref_handler_mod.roc_auc_score
    ref_handler_mod.roc_auc_score = lambda *a, **k: np.float64(_orig_auc(*a, **k))

    set_seed(42)
    Xt = torch.tensor(X, dtype=torch.float32)
    yt = torch.tensor(y, dtype=torch.long)
    handler = RefHandler(Xt, yt, test_size=0.2)
    dispatcher = RefDispatcher(handler, n=N_NODES, eval_on_user=False)
    topology = nx.to_numpy_array(
        nx.random_regular_graph(DEGREE, N_NODES, seed=42))
    proto = TorchModelHandler(
        net=RefLogReg(X.shape[1], 2), optimizer=torch.optim.SGD,
        optimizer_params={"lr": 0.1}, criterion=torch.nn.CrossEntropyLoss(),
        local_epochs=1, batch_size=32,
        create_model_mode=CreateModelMode.MERGE_UPDATE)
    nodes = GossipNode.generate(data_dispatcher=dispatcher,
                                p2p_net=StaticP2PNetwork(N_NODES, topology),
                                model_proto=proto, round_len=ROUND_LEN, sync=True)
    simulator = RefSimulator(nodes=nodes, data_dispatcher=dispatcher,
                             delta=ROUND_LEN,
                             protocol=AntiEntropyProtocol.PUSH,
                             delay=ConstantDelay(0),
                             online_prob=1.0, drop_prob=0.0, sampling_eval=0.0)
    report = SimulationReport()
    simulator.add_receiver(report)
    simulator.init_nodes(seed=42)
    t0 = time.perf_counter()
    with contextlib.redirect_stdout(sys.stderr):
        simulator.start(n_rounds=BASELINE_ROUNDS)
    elapsed = time.perf_counter() - t0
    print(f"[bench] reference: {BASELINE_ROUNDS} rounds in {elapsed:.2f}s "
          f"({BASELINE_ROUNDS/elapsed:.2f} r/s)", file=sys.stderr)
    return BASELINE_ROUNDS / elapsed


def bench_to_accuracy(X, y, target: float) -> None:
    """Secondary north-star: wall-clock for OUR side to reach ``target``
    global test accuracy (BASELINE.json "wall-clock to target test-acc") on
    the bench config. The reference comparison point is derived from its
    measured rounds/s (see BASELINE.md) rather than run here — at ~1 round/s
    a live reference run of this mode would take minutes per invocation.
    Not part of the driver's one-line contract; run with
    ``python bench.py --to-acc 0.9``."""
    import jax

    sim = build_sim(X, y)
    key = jax.random.PRNGKey(42)
    chunk = 20
    state = sim.init_nodes(key)
    s_warm, _ = sim.start(state, n_rounds=chunk, key=key)  # compile
    jax.block_until_ready(s_warm.model.params)

    state = sim.init_nodes(key)
    t0 = time.perf_counter()
    rounds_done, hit_at = 0, None
    while rounds_done < 400 and hit_at is None:
        state, report = sim.start(state, n_rounds=chunk, key=key)
        accs = report.curves(local=False)["accuracy"]
        for i, a in enumerate(accs):
            if a >= target:
                hit_at = rounds_done + i + 1
                break
        rounds_done += chunk
    elapsed = time.perf_counter() - t0
    if hit_at is None:
        print(f"[to-acc] ours: target {target} NOT reached in "
              f"{rounds_done} rounds ({elapsed:.2f}s)")
    else:
        print(f"[to-acc] ours: target {target} reached at round {hit_at} "
              f"in {elapsed:.2f}s wall")


def _peak_flops_table() -> dict:
    """The per-chip bf16 peak table now lives in ONE place —
    ``gossipy_tpu.telemetry.cost.PEAK_FLOPS`` — shared by this bench, the
    RunManifest ``perf`` block and the scale ladder, so the MFU
    denominator cannot drift between them. (Deferred import: importing
    the package pulls in jax, and bench's module import must stay
    jax-free so argv errors and the degrade re-exec never touch a
    possibly-wedged plugin.)"""
    from gossipy_tpu.telemetry.cost import PEAK_FLOPS
    return PEAK_FLOPS


def __getattr__(name: str):
    # Back-compat module attribute (tests and external callers read
    # ``bench.PEAK_FLOPS``), resolved lazily through the one shared
    # definition above.
    if name == "PEAK_FLOPS":
        return _peak_flops_table()
    raise AttributeError(name)


def bench_mfu(rounds: int = 50, n_nodes: int | None = None,
              n_train: int | None = None, n_test: int | None = None,
              variant: str = "vanilla", eval_every: int = 5,
              compact: bool = True, reps: int = 0) -> None:
    """Model-FLOPs-utilization for the CNN north-star config.

    Runs the CIFAR-10 100-node CNN round program (CIFAR-shaped synthetic
    data — utilization depends on shapes, not values), takes total FLOPs
    from XLA's own cost model on the compiled scan, and divides achieved
    FLOP/s by the chip's peak. Prints ONE JSON line. ``vs_baseline`` is
    reported against 1.0 "full chip" (the reference cannot run this
    workload on an accelerator at all, so there is no reference MFU).

    ``variant="all2all"`` measures the same CNN workload under the
    Koloskova All-to-All protocol (reference simul.py:720-852) instead of
    vanilla push gossip. The two protocols bound the engine's MFU range
    from both ends: vanilla semantics process each received message
    individually (per-mailbox-slot masked train passes over the whole
    population — ~24% average utilization at Poisson(1) in-degree), while
    All2All merges the whole neighborhood in ONE ``W_eff @ P`` einsum and
    trains each node exactly once per round (no masked waste). Both are
    reference-exact protocols; the spread between their MFU rows is the
    cost of per-message semantics, not engine overhead.

    ``eval_every`` amortizes the evaluation pass over that many rounds
    (round-3 phase attribution put eval at ~2/3 of round time; the
    reference's *per-round* eval is a semantic, not a perf contract —
    VERDICT r3 #1). FLOP accounting stays honest under the amortization:
    per-round FLOPs are decomposed into base + eval via two 1-round
    compiles (eval structurally on / structurally absent), and executed
    FLOPs = rounds * base + n_eval_rounds * eval — the timed program only
    pays eval on the rounds that actually run it.

    ``n_nodes``/``n_train``/``n_test`` override the workload size (smoke
    tests; the measured MFU is only meaningful at the default scale).

    Round-5 accounting note: the vanilla sim runs with the engine's default
    ``compact_deliver`` (auto-on at this scale — slots >= 1 run at a
    gathered static capacity instead of full-width masked). XLA's HLO cost
    model prices the compact/full ``lax.cond`` at its LARGER branch
    (verified: on/off 1-round programs count within 228 FLOPs of each
    other at the 100-node LogReg config), so the numerator stays the
    canonical full-width program's count while compaction cuts the time —
    the quoted fraction is throughput against the canonical workload
    (the same definition every earlier MFU row used), not a hardware FLOP
    counter. ``raw.compact_cap`` records the active capacity.
    """
    if variant not in ("vanilla", "all2all"):
        raise ValueError(f"unknown MFU variant {variant!r} "
                         "(a typo must not silently measure vanilla)")
    import jax
    import jax.numpy as jnp
    import optax

    from gossipy_tpu.core import AntiEntropyProtocol, CreateModelMode, \
        Topology, uniform_mixing
    from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
    from gossipy_tpu.handlers import SGDHandler, WeightedSGDHandler, losses
    from gossipy_tpu.models import CIFAR10Net
    from gossipy_tpu.simulation import All2AllGossipSimulator, GossipSimulator

    rng = np.random.default_rng(0)
    # The CPU fallback cannot finish the full CNN/100-node workload in
    # reasonable time on this 1-core host (~2.1 s per warm 8-node round in
    # fp32 since the einsum-conv default — was ~27 s under the grouped-conv
    # lowering); shrink it and compute in fp32 — the run is labeled
    # degraded and MFU is null off-TPU anyway (unknown device kind), so
    # only the smoke value (finite ms/round) matters.
    if n_nodes is None:
        n_nodes = 8 if DEGRADED else N_NODES
    if n_train is None:
        n_train = 256 if DEGRADED else 12800
    if n_test is None:
        n_test = 64 if DEGRADED else 1280
    rounds = 1 if DEGRADED else rounds
    reps = min(reps, 2) if DEGRADED else reps  # smoke only off-accelerator
    Xtr = rng.normal(size=(n_train, 32, 32, 3)).astype(np.float32)
    ytr = rng.integers(0, 10, n_train)
    Xte = rng.normal(size=(n_test, 32, 32, 3)).astype(np.float32)
    yte = rng.integers(0, 10, n_test)

    handler_cls = WeightedSGDHandler if variant == "all2all" else SGDHandler
    handler = handler_cls(
        model=CIFAR10Net(), loss=losses.cross_entropy,
        optimizer=optax.chain(optax.add_decayed_weights(1e-3), optax.sgd(0.05)),
        local_epochs=1, batch_size=32, n_classes=10, input_shape=(32, 32, 3),
        create_model_mode=CreateModelMode.MERGE_UPDATE,
        compute_dtype=None if DEGRADED else jnp.bfloat16)
    disp = DataDispatcher(ClassificationDataHandler(Xtr, ytr, Xte, yte),
                          n=n_nodes, eval_on_user=False)
    topo = Topology.random_regular(n_nodes, min(DEGREE, n_nodes - 1), seed=42,
                                   backend="networkx")
    stacked = disp.stacked()
    # Three structurally-different round programs over the same workload:
    # the TIMED one (eval amortized over eval_every rounds), plus two
    # 1-round FLOP-decomposition programs — eval forced every round vs eval
    # structurally absent (no eval keys in the data dict) — whose per-round
    # FLOP difference is the eval pass's cost in XLA's own count.
    no_eval = {k: v for k, v in stacked.items()
               if k not in ("x_eval", "y_eval", "xte", "yte", "mte")}

    def make_sim(data, ev):
        if variant == "all2all":
            return All2AllGossipSimulator(
                handler, topo, data, delta=ROUND_LEN,
                mixing=uniform_mixing(topo), sampling_eval=0.1,
                eval_every=ev)
        return GossipSimulator(
            handler, topo, data, delta=ROUND_LEN,
            protocol=AntiEntropyProtocol.PUSH, sampling_eval=0.1,
            eval_every=ev,
            # compact=False: the on-chip A/B control (--mfu-wide) — the
            # full-width masked slot passes the round-3 MFU row measured.
            compact_deliver=None if compact else False)

    sim = make_sim(stacked, eval_every)
    import jax.random as jrandom
    key = jrandom.PRNGKey(42)
    state = sim.init_nodes(key, common_init=True)

    from gossipy_tpu.telemetry.cost import cost_report_for

    cost_reports = {}

    def flops_of_one_round(s, label: str) -> float | None:
        # XLA's HLO cost model counts a while/scan body ONCE regardless
        # of trip count (verified: 1-round and 10-round programs report
        # equal flops), so a 1-round program gives per-round FLOPs
        # directly. The capture is telemetry.cost.CostReport — the same
        # record the perf= layer banks — so the row also gets the
        # program's memory_analysis() numbers for free.
        cr = cost_report_for(s, state, key, n_rounds=1, label=label)
        if cr is not None:
            cost_reports[label] = cr
        return cr.flops if cr is not None else None

    # Rounds on which _maybe_eval actually evaluates (incl. the forced
    # final-round eval).
    n_evals = sum(1 for r in range(rounds)
                  if (r + 1) % eval_every == 0 or r == rounds - 1)
    f_with_eval = flops_of_one_round(make_sim(stacked, 1), "with_eval")
    if DEGRADED or eval_every == 1:
        # Off-accelerator MFU is null anyway (unknown device kind) — skip
        # the second CNN compile and fall back to the undecomposed count.
        f_base = None
        flops_total = (f_with_eval * rounds
                       if f_with_eval is not None else None)
    else:
        f_base = flops_of_one_round(make_sim(no_eval, 1), "base")
        if f_with_eval is not None and f_base is not None:
            flops_total = rounds * f_base + \
                n_evals * max(f_with_eval - f_base, 0.0)
        else:
            flops_total = None

    if reps > 0:
        # Seed-batched throughput (VERDICT r4 #1 lever 3): S independent
        # simulations in ONE vmapped program — per-node math gains a seed
        # batch dim that feeds the MXU. Executed FLOPs = S x the
        # single-seed count (compaction is off under the seed vmap — a
        # batched cond predicate would execute both branches — which
        # matches the single-seed count's larger-branch pricing). The
        # repetition program re-inits per seed; init cost is excluded from
        # the FLOP numerator, so the quoted MFU is slightly conservative.
        keys = jrandom.split(key, reps)
        _ = sim.run_repetitions(rounds, keys, common_init=True)  # compile
        t0 = time.perf_counter()
        states, _ = sim.run_repetitions(rounds, keys, common_init=True)
        jax.block_until_ready(states.model.params)
        elapsed = time.perf_counter() - t0
        if flops_total is not None:
            flops_total *= reps
    else:
        s2, _ = sim.start(state, n_rounds=rounds, key=key,  # warmup/compile
                          donate_state=False)
        jax.block_until_ready(s2.model.params)
        t0 = time.perf_counter()
        s3, _ = sim.start(state, n_rounds=rounds, key=key)
        jax.block_until_ready(s3.model.params)
        elapsed = time.perf_counter() - t0

    emit_manifest(sim, f"mfu/{variant}")
    achieved = flops_total / elapsed if flops_total is not None else None
    kind = jax.devices()[0].device_kind
    peak = _peak_flops_table().get(kind)
    if peak is None:
        print(f"[mfu] WARNING: unknown device_kind {kind!r} — MFU will be "
              "null. Add this chip's bf16 dense-matmul peak (FLOP/s) to "
              "PEAK_FLOPS in gossipy_tpu/telemetry/cost.py to get a "
              "value.", file=sys.stderr)
    mfu = achieved / peak if (peak and achieved is not None) else None
    print(f"[mfu] {kind}: {rounds} rounds in {elapsed:.2f}s "
          f"({elapsed / rounds * 1e3:.1f} ms/round)"
          + (f", XLA-counted {flops_total / 1e12:.2f} TFLOP total -> "
             f"{achieved / 1e12:.2f} TFLOP/s achieved"
             if achieved is not None else ", no XLA flops count")
          + (f", peak {peak / 1e12:.0f} -> MFU {mfu:.4f}" if mfu is not None
             else " (MFU null)"),
          file=sys.stderr)
    emit({
        "metric": "mfu_cifar10_100nodes_cnn" + (
            "_all2all" if variant == "all2all" else "") + (
            "" if compact else "_widepass") + (
            f"_reps{reps}" if reps else ""),
        "value": round(mfu, 4) if mfu is not None else None,
        "unit": "fraction_of_peak",
        "vs_baseline": round(mfu, 4) if mfu is not None else None,
        "raw": {
            "device_kind": kind,
            "protocol": variant,
            "n_nodes": n_nodes,
            # The seed-batched program runs with compaction forced off (a
            # vmapped cond predicate executes both branches) even when the
            # simulator carries a cap — report what the TIMED program did.
            "compact_cap": (None if reps
                            else getattr(sim, "_compact_cap", None)),
            "eval_every": eval_every,
            "n_eval_rounds": n_evals,
            "ms_per_round": round(elapsed / rounds * 1e3, 2),
            # The uniform perf-stamp trio every bench row now carries
            # (telemetry.cost): the on-chip evidence banks itself the
            # moment a TPU window opens, with zero extra work.
            "mfu_est": round(mfu, 4) if mfu is not None else None,
            "flops_per_round": f_with_eval,
            "hbm_peak_bytes": (cost_reports["with_eval"].peak_bytes
                               if "with_eval" in cost_reports else None),
            "xla_flops_per_round_with_eval": f_with_eval,
            "xla_flops_per_round_base": f_base,
            "xla_flops_executed_total": flops_total,
            "achieved_tflops_per_sec": (round(achieved / 1e12, 3)
                                        if achieved is not None else None),
            "peak_tflops_per_sec": peak / 1e12 if peak else None,
            "rounds": rounds,
            "seed_batch": reps or None,
            "note": "MFU vs single-chip bf16 peak; no reference MFU exists "
                    "(the reference cannot run this workload on an "
                    "accelerator)",
        },
    })


def _scale_harness(n_nodes: int, rounds: int, build_sim):
    """Shared scaffolding for the scale rows: synthetic spambase-shaped
    data (4 samples/node), capped evaluation, compile + timed double run.

    Evaluation memory scales as [eval-nodes x eval-samples]: an uncapped
    20% eval split at 50k nodes is a [50k, 40k] score tensor (~16+ GB, OOM
    on a single chip). The eval set is capped and a 1% node sample is
    evaluated on the final round only — the metric is engine throughput,
    not the learning curve.

    ``build_sim(feature_dim, disp) -> (sim, build_seconds)`` constructs
    the handler + topology/mixing + simulator and reports its own
    topology-build time. Returns
    ``(rounds_per_sec, final_accuracy, build_seconds)``.
    """
    import jax

    from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher

    d = 57
    rng = np.random.default_rng(42)
    w = rng.normal(size=d)
    X = rng.normal(size=(4 * n_nodes, d)).astype(np.float32)
    y = (X @ w > 0).astype(np.int64)
    eval_cap = min(2048, int(0.2 * len(X)))  # a cap, not a floor: small
    disp = DataDispatcher(                   # runs keep a 20% split
        ClassificationDataHandler(X, y, test_size=eval_cap / len(X)),
        n=n_nodes, eval_on_user=False)

    def stamp(phase):
        # Forensics for the round-3 on-TPU crash (rc=1 at ~27 min with the
        # traceback lost): phase-stamped progress makes the crash point
        # attributable from evidence_logs/<tag>.err alone, even if the
        # process dies without a traceback again.
        print(f"[scale] {time.strftime('%H:%M:%S')} {phase}",
              file=sys.stderr, flush=True)

    stamp("building topology+simulator")
    sim, build_s = build_sim(d, disp)
    budget = sim.memory_budget()
    stamp("memory budget: " + ", ".join(
        f"{k}={v / 2**20:.1f}MB" for k, v in budget.items()
        if k.endswith("_bytes") and v is not None))
    key = jax.random.PRNGKey(42)
    stamp("init_nodes")
    state = sim.init_nodes(key)
    stamp(f"compile+first {rounds}-round run")
    s2, _ = sim.start(state, n_rounds=rounds, key=key,  # compile; keep the
                      donate_state=False)               # state for the
    jax.block_until_ready(s2.model.params)              # timed rerun
    stamp("timed run")
    t0 = time.perf_counter()
    s3, report = sim.start(state, n_rounds=rounds, key=key)
    jax.block_until_ready(s3.model.params)
    elapsed = time.perf_counter() - t0
    stamp("done")
    stamp_wire_traffic(sim, report, rounds)
    stamp_perf(sim)
    emit_manifest(sim, "scale")
    acc = report.curves(local=False)["accuracy"][-1]
    return rounds / elapsed, float(acc), build_s


def bench_scale(n_nodes: int = 50_000, rounds: int = 100) -> None:
    """Scale row: gossip rounds/sec at ``n_nodes`` (default 50k).

    Uses :class:`SparseTopology` (CSR neighbor lists, O(E) memory) — the
    representation that breaks the dense [N, N] wall BOTH engines share at
    round 1 (ours: core.Topology; reference: StaticP2PNetwork,
    core.py:311-361 — a 50k-node dense adjacency is ~2.5 GB before the
    simulation even starts, and the reference's Python round loop would
    need hours per round at this node count, so there is no reference
    number to compare against).
    """
    import optax

    from gossipy_tpu.core import AntiEntropyProtocol, CreateModelMode, \
        SparseTopology
    from gossipy_tpu.handlers import SGDHandler, losses
    from gossipy_tpu.models import LogisticRegression
    from gossipy_tpu.simulation import GossipSimulator

    def build_sim(d, disp):
        handler = SGDHandler(model=LogisticRegression(d, 2),
                             loss=losses.cross_entropy,
                             optimizer=optax.sgd(0.1),
                             local_epochs=1, batch_size=4, n_classes=2,
                             input_shape=(d,),
                             create_model_mode=CreateModelMode.MERGE_UPDATE)
        t0 = time.perf_counter()
        topo = SparseTopology.random_regular(n_nodes, DEGREE, seed=42)
        build_s = time.perf_counter() - t0
        sim = GossipSimulator(handler, topo, disp.stacked(), delta=ROUND_LEN,
                              protocol=AntiEntropyProtocol.PUSH,
                              sampling_eval=0.01, eval_every=rounds,
                              history_dtype=HISTORY_DTYPE, perf=True)
        return sim, build_s

    rate, acc, build_s = _scale_harness(n_nodes, rounds, build_sim)
    print(f"[scale] {n_nodes} nodes: topology {build_s:.2f}s, {rounds} "
          f"rounds at {rate:.1f} r/s, final acc {acc:.3f}", file=sys.stderr)
    emit({
        "metric": f"sim_rounds_per_sec_{n_nodes}nodes",
        "value": round(rate, 2),
        "unit": "rounds/s",
        "vs_baseline": None,
        "raw": {
            **PERF_INFO,
            "n_nodes": n_nodes,
            "degree": DEGREE,
            "rounds": rounds,
            "topology_build_seconds": round(build_s, 2),
            "final_global_accuracy": round(acc, 4),
            "note": "no reference baseline exists: a dense 50k-node "
                    "adjacency (~2.5 GB) plus a per-object Python round "
                    "loop is out of the reference's reach",
        },
    })


def bench_cohort(nominal_n: int = 1_000_000, rounds: int = 50) -> None:
    """Cohort row: active-cohort rounds/sec at NOMINAL ``nominal_n``.

    The scale rows materialize every node (the 50k on-TPU wall,
    ``BENCH_TPU_EVIDENCE.jsonl`` row 3); this row runs the same LogReg
    round shape through ``simulation.cohort`` — the nominal population
    lives as a host-resident pool and each round materializes only
    ``$GOSSIPY_TPU_COHORT_SIZE`` nodes (default 1024) — so the metric is
    per-round cost DECOUPLED from N. ``memory_budget``'s cohort-aware
    accounting (``cohort_pool_resident`` vs ``cohort_active_total`` vs
    the materialized prediction) is stamped into ``raw.*``.
    """
    import jax
    import optax

    from gossipy_tpu.core import AntiEntropyProtocol, CreateModelMode
    from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
    from gossipy_tpu.handlers import SGDHandler, losses
    from gossipy_tpu.models import LogisticRegression
    from gossipy_tpu.simulation import CohortConfig, GossipSimulator, \
        NominalTopology

    cohort_size = int(os.environ.get("GOSSIPY_TPU_COHORT_SIZE", "1024"))
    cohort_size = min(cohort_size, nominal_n)
    d = 57
    rng = np.random.default_rng(42)
    w = rng.normal(size=d)
    # Data bank: P = 4C shards; node i reads shard i % P (at nominal 10M
    # nobody stacks 10M distinct shards — the bank is part of the
    # cohort scaling story, not a shortcut).
    pool_shards = min(nominal_n, 4 * cohort_size)
    X = rng.normal(size=(4 * pool_shards, d)).astype(np.float32)
    y = (X @ w > 0).astype(np.int64)
    eval_cap = min(2048, int(0.2 * len(X)))
    disp = DataDispatcher(
        ClassificationDataHandler(X, y, test_size=eval_cap / len(X)),
        n=pool_shards, eval_on_user=False)

    def stamp(phase):
        print(f"[cohort] {time.strftime('%H:%M:%S')} {phase}",
              file=sys.stderr, flush=True)

    handler = SGDHandler(model=LogisticRegression(d, 2),
                         loss=losses.cross_entropy,
                         optimizer=optax.sgd(0.1),
                         local_epochs=1, batch_size=4, n_classes=2,
                         input_shape=(d,),
                         create_model_mode=CreateModelMode.MERGE_UPDATE)
    stamp(f"building cohort simulator (nominal {nominal_n}, C "
          f"{cohort_size})")
    sim = GossipSimulator(handler, NominalTopology(nominal_n),
                          disp.stacked(), delta=ROUND_LEN,
                          protocol=AntiEntropyProtocol.PUSH,
                          sampling_eval=0.01, eval_every=rounds,
                          history_dtype=HISTORY_DTYPE,
                          cohort=CohortConfig(size=cohort_size), perf=True)
    budget = sim.memory_budget()
    stamp("cohort budget: pool "
          f"{budget['cohort_pool_resident'] / 2**20:.1f}MB resident, "
          f"active {budget['cohort_active_total'] / 2**20:.1f}MB, "
          "materialized prediction "
          f"{budget['cohort_materialized_prediction'] / 2**20:.1f}MB")
    key = jax.random.PRNGKey(42)
    stamp("init_cohort_pool")
    t_pool = time.perf_counter()
    pool = sim.init_cohort_pool(key)
    pool_s = time.perf_counter() - t_pool
    stamp(f"compile+first {rounds}-round segment loop")
    pool, _ = sim.start(pool, n_rounds=rounds, key=key)
    stamp("timed run")
    t0 = time.perf_counter()
    pool, report = sim.start(pool, n_rounds=rounds, key=key)
    elapsed = time.perf_counter() - t0
    stamp("done")
    stamp_perf(sim)
    emit_manifest(sim, "cohort")
    rate = rounds / elapsed
    cov = float(report.cohort_coverage[-1])
    print(f"[cohort] nominal {nominal_n}: pool init {pool_s:.2f}s, "
          f"{rounds} rounds at {rate:.1f} r/s, coverage {cov:.4f}",
          file=sys.stderr)

    # Streaming A/B: the same config with a prefetch depth. Timed runs
    # are untraced (apples to apples with the serial row above); traced
    # runs from freshly re-inited pools supply the overlap account AND
    # the bit-identity check the streaming driver promises.
    from gossipy_tpu.telemetry.tracing import Tracer, trace_report
    prefetch = int(os.environ.get("GOSSIPY_TPU_COHORT_PREFETCH", "2"))

    def build_cohort_sim(prefetch, tracing=None):
        return GossipSimulator(handler, NominalTopology(nominal_n),
                               disp.stacked(), delta=ROUND_LEN,
                               protocol=AntiEntropyProtocol.PUSH,
                               sampling_eval=0.01, eval_every=rounds,
                               history_dtype=HISTORY_DTYPE,
                               cohort=CohortConfig(size=cohort_size,
                                                   prefetch=prefetch),
                               perf=True, tracing=tracing)

    stamp(f"streaming A/B (prefetch {prefetch}): warm + timed")
    sim_st = build_cohort_sim(prefetch)
    pool_st = sim_st.init_cohort_pool(key)
    pool_st, _ = sim_st.start(pool_st, n_rounds=rounds, key=key)
    t0 = time.perf_counter()
    pool_st, _ = sim_st.start(pool_st, n_rounds=rounds, key=key)
    stream_elapsed = time.perf_counter() - t0
    stream_speedup = elapsed / stream_elapsed

    def traced_frac(prefetch):
        tr = Tracer(process_name=f"bench.cohort.p{prefetch}")
        s = build_cohort_sim(prefetch, tracing=tr)
        p, _ = s.start(s.init_cohort_pool(key), n_rounds=rounds, key=key)
        tot = trace_report(tr.snapshot())["totals"]
        return (tot["overlap_frac"] or 0.0, tot["host_blocked_frac"] or 0.0,
                jax.tree.leaves(p))

    overlap_frac, blocked_frac, leaves_st = traced_frac(prefetch)
    serial_overlap_frac, serial_blocked_frac, leaves_se = traced_frac(0)
    bit_identical = all(np.array_equal(np.asarray(a), np.asarray(b))
                        for a, b in zip(leaves_se, leaves_st))
    if not bit_identical:
        raise AssertionError(
            "streaming cohort run diverged from serial — the prefetch "
            "pipeline must be bit-identical")
    print(f"[cohort] streaming prefetch={prefetch}: {stream_speedup:.2f}x "
          f"vs serial, overlap_frac {overlap_frac:.3f} (serial "
          f"{serial_overlap_frac:.3f}), bit-identical", file=sys.stderr)
    emit({
        "metric": f"cohort_rounds_per_sec_{nominal_n}nominal",
        "value": round(rate, 2),
        "unit": "rounds/s",
        "vs_baseline": None,
        "raw": {
            **PERF_INFO,
            "nominal_n": nominal_n,
            "cohort_size": cohort_size,
            "rounds": rounds,
            "pool_init_seconds": round(pool_s, 2),
            "pool_bytes": budget["cohort_pool_resident"],
            "active_bytes": budget["cohort_active_total"],
            "materialized_prediction_bytes":
                budget["cohort_materialized_prediction"],
            "pool_coverage_final": round(cov, 6),
            "stream_prefetch": prefetch,
            "stream_speedup": round(stream_speedup, 3),
            "overlap_frac": round(overlap_frac, 4),
            "host_blocked_frac": round(blocked_frac, 4),
            "serial_overlap_frac": round(serial_overlap_frac, 4),
            "serial_host_blocked_frac": round(serial_blocked_frac, 4),
            "stream_bit_identical": bit_identical,
            "note": "per-round cost is a function of C, not N: the "
                    "materialized engine cannot build this row at all "
                    "past ~50k nodes on one chip; stream_* fields are "
                    "the prefetch-pipeline A/B on the same config",
        },
    })


def bench_scale_all2all(n_nodes: int = 50_000, rounds: int = 50) -> None:
    """Variant scale row: Koloskova All-to-All (mixing merge) rounds/sec at
    ``n_nodes`` over a :class:`SparseTopology` with O(E) ``SparseMixing``
    edge weights — the round-3 segment-sum path. The reference's
    ``MixingMatrix``/``All2AllGossipSimulator`` (core.py:392-453,
    simul.py:720-852) are dense-only on top of a per-object Python loop, so
    no reference number exists at this node count.
    """
    import optax

    from gossipy_tpu.core import CreateModelMode, SparseTopology, \
        uniform_mixing
    from gossipy_tpu.handlers import WeightedSGDHandler, losses
    from gossipy_tpu.models import LogisticRegression
    from gossipy_tpu.simulation import All2AllGossipSimulator

    def build_sim(d, disp):
        handler = WeightedSGDHandler(
            model=LogisticRegression(d, 2), loss=losses.cross_entropy,
            optimizer=optax.sgd(0.1), local_epochs=1, batch_size=4,
            n_classes=2, input_shape=(d,),
            create_model_mode=CreateModelMode.MERGE_UPDATE)
        t0 = time.perf_counter()
        topo = SparseTopology.random_regular(n_nodes, DEGREE, seed=42)
        mixing = uniform_mixing(topo)
        build_s = time.perf_counter() - t0
        sim = All2AllGossipSimulator(handler, topo, disp.stacked(),
                                     delta=ROUND_LEN, mixing=mixing,
                                     sampling_eval=0.01, eval_every=rounds,
                                     perf=True)
        return sim, build_s

    rate, acc, build_s = _scale_harness(n_nodes, rounds, build_sim)
    print(f"[scale-all2all] {n_nodes} nodes: build {build_s:.2f}s, {rounds} "
          f"rounds at {rate:.1f} r/s, final acc {acc:.3f}", file=sys.stderr)
    emit({
        "metric": f"all2all_rounds_per_sec_{n_nodes}nodes",
        "value": round(rate, 2),
        "unit": "rounds/s",
        "vs_baseline": None,
        "raw": {
            **PERF_INFO,
            "n_nodes": n_nodes,
            "degree": DEGREE,
            "rounds": rounds,
            "topology_and_mixing_build_seconds": round(build_s, 2),
            "final_global_accuracy": round(acc, 4),
            "note": "sparse O(E) mixing merge (auto form: padded "
                    "gather+einsum on TPU, sorted segment-sum on CPU); the "
                    "reference's All2All simulator is dense-only Python",
        },
    })


def bench_ring_attention(s_len: int = 8192) -> None:
    """Flash-attention kernel vs XLA dense attention at sequence ``s_len``.

    Single-chip, one head, head dim 128, bf16, causal — the kernel's
    design regime (the [S, S] score block stays in VMEM instead of
    round-tripping HBM between the two matmuls). The flash leg is
    TPU-only: pallas interpreter mode is not a meaningful timing, so
    off-TPU the row carries the dense timing plus an explicit skip reason
    (the fused-regime pattern). Prints ONE JSON line.
    """
    import jax
    import jax.numpy as jnp

    from gossipy_tpu.ops.attention import flash_attention

    if DEGRADED:
        s_len = min(s_len, 512)
    dim = 128
    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (s_len, dim), jnp.bfloat16)
    k = jax.random.normal(kk, (s_len, dim), jnp.bfloat16)
    v = jax.random.normal(kv, (s_len, dim), jnp.bfloat16)

    def dense(q, k, v):
        s = (q.astype(jnp.float32) @ k.astype(jnp.float32).T
             ) / np.sqrt(dim)
        i = jnp.arange(s_len)
        s = jnp.where(i[None, :] > i[:, None], -1e30, s)
        return (jax.nn.softmax(s, axis=-1) @ v.astype(jnp.float32)
                ).astype(q.dtype)

    reps = 20

    def time_fn(fn) -> float:
        f = jax.jit(fn)
        out = f(q, k, v)
        jax.block_until_ready(out)  # compile
        t0 = time.perf_counter()
        for _ in range(reps):
            out = f(q, k, v)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / reps * 1e3

    dense_ms = time_fn(dense)
    flash_ms = None
    err = None
    if jax.default_backend() != "tpu":
        err = ("flash leg skipped off-TPU (pallas interpreter mode is not "
               "a meaningful timing)")
    else:
        try:
            flash_ms = time_fn(
                lambda q, k, v: flash_attention(q, k, v, causal=True))
        except Exception as e:  # kernel unavailable on this backend
            err = repr(e)[:200]
    parity = None
    if flash_ms is not None:
        # On-silicon fwd+bwd parity for the hand-derived custom vjp
        # (VERDICT r3 #3): interpreter-mode tests cannot catch a Mosaic
        # compilation/layout bug, and the kernel is the DEFAULT TPU path of
        # ring_attention — assert values AND gradients against XLA dense at
        # f32, in the same JSON row the evidence file banks. Guarded: a
        # bwd-kernel compile failure (first-ever Mosaic build of the vjp
        # happens HERE) must land as parity.error in the row, not crash
        # away the timings already measured.
        try:
            parity = _attention_parity(
                dense, lambda q_, k_, v_: flash_attention(q_, k_, v_,
                                                          causal=True),
                q, k, v)
        except Exception as e:
            parity = {"pass": False, "error": repr(e)[:300]}
    print(f"[ring-attn] S={s_len}: dense {dense_ms:.2f} ms, flash "
          f"{flash_ms if flash_ms is None else round(flash_ms, 2)} ms"
          + (f" (error: {err})" if err else "")
          + (f"; parity {'PASS' if parity['pass'] else 'FAIL'} "
             f"({parity.get('error') or _parity_desc(parity)})"
             if parity else ""),
          file=sys.stderr)
    speedup = (dense_ms / flash_ms) if flash_ms else None
    emit({
        "metric": "flash_attention_speedup",
        "value": round(speedup, 3) if speedup else None,
        "unit": "x_vs_xla_dense",
        "vs_baseline": round(speedup, 3) if speedup else None,
        "raw": {
            "s_len": s_len, "head_dim": dim, "dtype": "bfloat16",
            "causal": True, "reps": reps,
            "dense_ms": round(dense_ms, 3),
            "flash_ms": (round(flash_ms, 3)
                         if flash_ms is not None else None),
            "parity": parity,
            "error": err,
            "note": "single chip, one head; the sequence-parallel form is "
                    "collectives.ring_attention(flash=True)",
        },
    })


def _parity_desc(parity: dict) -> str:
    """Human line for the parity dict; non-finite errors arrive as STRINGS
    (json sanitization), so no %.2e on them."""
    def fmt(v):
        return f"{v:.2e}" if isinstance(v, float) else str(v)
    return (f"fwd {fmt(parity['fwd_max_abs_err'])}, "
            f"grad {fmt(parity['grad_max_abs_err'])}")


def _attention_parity(dense_fn, flash_fn, q, k, v,
                      tol: float = 5e-3) -> dict:
    """Forward + gradient agreement of two attention implementations at
    f32, as JSON-ready floats. ``pass`` uses an absolute tolerance scaled
    to unit-variance inputs (softmax reduction-order differences at long
    sequence lengths stay ~1e-5; 5e-3 flags real kernel bugs, not fp
    noise)."""
    import jax
    import jax.numpy as jnp
    qf, kf, vf = (x.astype(jnp.float32) for x in (q, k, v))

    def fwd_f32(fn):
        return fn(qf, kf, vf).astype(jnp.float32)

    o_d, o_f = fwd_f32(dense_fn), fwd_f32(flash_fn)
    fwd_err = float(jnp.max(jnp.abs(o_d - o_f)))

    def loss(fn):
        return lambda args: (fn(*args).astype(jnp.float32) ** 2).mean()

    g_d = jax.grad(loss(dense_fn))((qf, kf, vf))
    g_f = jax.grad(loss(flash_fn))((qf, kf, vf))
    grad_err = max(float(jnp.max(jnp.abs(a - b))) for a, b in zip(g_d, g_f))
    # Gradients of a mean-loss shrink with size; compare relative to their
    # own scale so "both tiny" cannot mask a broken vjp, with a small
    # absolute floor for the degenerate all-zero case.
    g_scale = max(float(jnp.max(jnp.abs(g))) for g in g_d)
    import math

    def finite(x):
        # json.dumps would emit a bare (RFC-8259-invalid) NaN/Infinity
        # token and strict parsers would reject the whole evidence line —
        # exactly when a broken kernel makes the row matter most.
        return x if math.isfinite(x) else str(x)

    return {
        "fwd_max_abs_err": finite(fwd_err),
        "grad_max_abs_err": finite(grad_err),
        "grad_scale": finite(g_scale),
        # Non-finite errors are a hard fail (comparisons with nan are
        # False, so the boolean below already lands False — made explicit).
        "pass": bool(math.isfinite(fwd_err) and math.isfinite(grad_err)
                     and fwd_err < tol
                     and grad_err < max(2 * tol * g_scale, 1e-7)),
    }


def _deliver_phase_ms(sim, state, key, rounds: int):
    """Deliver-phase (``gossipy.receive_merge``) milliseconds per round
    from a profiler trace of ``rounds`` rounds — the direct per-phase
    signal (telemetry.cost), not a wall-clock difference. None when the
    runtime's trace carries no attributable phase durations."""
    import tempfile

    import jax

    from gossipy_tpu.telemetry import phase_times_from_trace
    from gossipy_tpu.telemetry.cost import hlo_op_phases
    from gossipy_tpu.telemetry.scopes import PHASE_RECEIVE_MERGE

    tmp = tempfile.mkdtemp(prefix="fused_deliver_trace_")
    try:
        tracer = jax.profiler.trace(tmp, create_perfetto_trace=True)
    except TypeError:  # older jax without the kwarg
        tracer = jax.profiler.trace(tmp)
    with tracer:
        s, _ = sim.start(state, n_rounds=rounds, key=key, donate_state=False)
        jax.block_until_ready(s.model.params)
    # CPU-runtime traces carry bare HLO op names; bridge through the
    # compiled program's own op_name scope metadata (TPU XProf dumps match
    # on the scope string directly and the map is a harmless no-op).
    try:
        op_map = hlo_op_phases(
            sim.lower_start(state, n_rounds=rounds, key=key)
            .compile().as_text())
    except Exception:
        op_map = None
    per_phase = phase_times_from_trace(tmp, op_to_phase=op_map)
    if per_phase is None or PHASE_RECEIVE_MERGE not in per_phase:
        return None
    return per_phase[PHASE_RECEIVE_MERGE] / rounds


def bench_fused_regime(rounds: int = 40, n: int = 64) -> None:
    """Pallas ``fused_merge`` in its design regime: CNN-sized params, clique
    fan-in with a K=4 mailbox, MERGE_UPDATE deliver.

    Three legs — plain (XLA gather+blend), ``per_slot`` (one kernel launch
    per mailbox slot, the pre-multi fused path kept for exactly this A/B)
    and ``multi`` (one launch drains all K slots). Wall-clock speedup is a
    TPU measurement (interpreter-mode wall clock is meaningless and the
    legs are skipped off-TPU, as before); the DELIVER-PHASE ms from
    ``phase_times_from_trace`` and the bytes-moved model are stamped on
    every backend — on CPU the interpreter runs the same launch schedule,
    so per_slot vs multi is a meaningful relative row (the K->1 launch
    collapse) even where absolute numbers are not. Prints ONE JSON line.
    ``n`` overrides the node count (smoke tests only).
    """
    import jax
    import jax.numpy as jnp
    import optax

    from gossipy_tpu.core import AntiEntropyProtocol, CreateModelMode, Topology
    from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
    from gossipy_tpu.handlers import SGDHandler, losses
    from gossipy_tpu.models import CIFAR10Net
    from gossipy_tpu.simulation import GossipSimulator

    # The degraded CPU fallback cannot afford the full clique-64 CNN
    # measurement (fp32 CNN rounds on this 1-core host are ~0.5 s each and
    # the mode compiles + traces THREE simulators); shrink it — the run is
    # labeled degraded and the wall-clock fused legs are skipped off-TPU,
    # so only finite plain/deliver numbers matter.
    if DEGRADED:
        rounds, n = min(rounds, 4), min(n, 16)
    K = 4  # mailbox depth: the K->1 launch collapse under measurement
    rng = np.random.default_rng(0)
    Xtr = rng.normal(size=(n * 64, 32, 32, 3)).astype(np.float32)
    ytr = rng.integers(0, 10, n * 64)
    disp = DataDispatcher(ClassificationDataHandler(Xtr, ytr, test_size=0.2),
                          n=n, eval_on_user=False)
    handler = SGDHandler(
        model=CIFAR10Net(), loss=losses.cross_entropy,
        optimizer=optax.sgd(0.05), local_epochs=1, batch_size=32,
        n_classes=10, input_shape=(32, 32, 3),
        create_model_mode=CreateModelMode.MERGE_UPDATE,
        # bf16 is the TPU measurement dtype; on CPU (smoke only — the
        # wall-clock fused runs are skipped there anyway) bf16 is emulated
        # and ~10x slower.
        compute_dtype=jnp.bfloat16 if jax.default_backend() == "tpu"
        else None)
    legs = {False: "plain", "per_slot": "per_slot", "multi": "multi"}

    def make_sim(fused):
        # perf=True on the plain leg: the row's uniform perf trio
        # (raw.mfu_est / flops_per_round / hbm_peak_bytes) comes from
        # the same config the plain timing measured.
        return GossipSimulator(handler, Topology.clique(n), disp.stacked(),
                               delta=ROUND_LEN,
                               protocol=AntiEntropyProtocol.PUSH,
                               eval_every=rounds, fused_merge=fused,
                               mailbox_slots=K, perf=fused is False)

    accepted: dict = {}

    def run(fused) -> float:
        sim = make_sim(fused)
        key = jax.random.PRNGKey(0)
        state = sim.init_nodes(key, common_init=True)
        s2, _ = sim.start(state, n_rounds=rounds, key=key,  # compile
                          donate_state=False)
        jax.block_until_ready(s2.model.params)
        t0 = time.perf_counter()
        s3, rep = sim.start(state, n_rounds=rounds, key=key)
        jax.block_until_ready(s3.model.params)
        accepted[legs[fused]] = (rep.sent_messages - rep.failed_messages) \
            / max(rounds, 1)
        if fused is False:
            stamp_perf(sim)
        return (time.perf_counter() - t0) / rounds * 1e3  # ms/round

    plain_ms = run(False)
    per_slot_ms = multi_ms = None
    err = None
    if jax.default_backend() != "tpu":
        err = ("fused path skipped off-TPU (pallas interpreter mode is "
               "not a meaningful timing)")
    else:
        try:
            per_slot_ms = run("per_slot")
            multi_ms = run("multi")
        except Exception as e:  # kernel unavailable on this backend
            err = repr(e)[:200]

    # Deliver-phase attribution runs on EVERY backend: relative per_slot
    # vs multi is the launch-schedule comparison the mode exists for. On
    # TPU the CNN legs themselves are traced; off-TPU a small LogReg
    # config with the IDENTICAL launch schedule stands in (tracing the
    # CNN through the interpreter costs several full recompiles, and only
    # the relative schedule is meaningful there anyway).
    if jax.default_backend() == "tpu":
        deliver_builder, d_rounds = make_sim, rounds
        deliver_config = {"model": "CIFAR10Net", "n_nodes": n}
    else:
        from gossipy_tpu.models import LogisticRegression
        d_n, d_dim, d_rounds = 16, 30, 8
        Xs = rng.normal(size=(d_n * 24, d_dim)).astype(np.float32)
        ys = (Xs @ rng.normal(size=d_dim) > 0).astype(np.int64)
        sdisp = DataDispatcher(
            ClassificationDataHandler(Xs, ys, test_size=0.2),
            n=d_n, eval_on_user=False)
        shandler = SGDHandler(
            model=LogisticRegression(d_dim, 2), loss=losses.cross_entropy,
            optimizer=optax.sgd(0.1), local_epochs=1, batch_size=8,
            n_classes=2, input_shape=(d_dim,),
            create_model_mode=CreateModelMode.MERGE_UPDATE)

        def deliver_builder(fused):
            return GossipSimulator(shandler, Topology.clique(d_n),
                                   sdisp.stacked(), delta=ROUND_LEN,
                                   protocol=AntiEntropyProtocol.PUSH,
                                   eval_every=d_rounds, fused_merge=fused,
                                   mailbox_slots=K)

        deliver_config = {"model": "LogisticRegression", "n_nodes": d_n}
    deliver_ms: dict = {}
    for fused, leg in legs.items():
        try:
            sim = deliver_builder(fused)
            key = jax.random.PRNGKey(0)
            state = sim.init_nodes(key, common_init=True)
            s2, _ = sim.start(state, n_rounds=d_rounds, key=key,
                              donate_state=False)  # compile outside trace
            jax.block_until_ready(s2.model.params)
            ms = _deliver_phase_ms(sim, state, jax.random.PRNGKey(0),
                                   d_rounds)
            deliver_ms[leg] = round(ms, 3) if ms is not None else None
        except Exception as e:
            deliver_ms[leg] = None
            print(f"[fused-regime] deliver trace ({leg}) failed: "
                  f"{repr(e)[:120]}", file=sys.stderr)

    # Bytes-moved model for ONE deliver phase (docs/performance.md "Fused
    # deliver"): every leg gathers the accepted peer rows off the ring at
    # wire width; the params matrix is read+written once per PASS — K
    # passes for plain and per_slot, one for multi — and the plain path
    # additionally materializes the gathered peer copy at receiver width.
    sim0 = make_sim(False)
    wire = sim0.wire_bytes_per_message()
    p_scalars, _ = sim0._history_param_counts()
    p_bytes = 4 * p_scalars  # receiver rows are fp32
    acc = accepted.get("plain", 0.0)
    gather = acc * wire

    def passes_bytes(passes, materialize=False):
        moved = passes * 2 * n * p_bytes + gather
        if materialize:
            moved += acc * p_bytes
        return int(round(moved))

    deliver_bytes = {
        "plain": passes_bytes(K, materialize=True),
        "per_slot": passes_bytes(K),
        "multi": passes_bytes(1),
        "accepted_per_round": round(acc, 2),
        "wire_bytes_per_message": wire,
    }

    print(f"[fused-regime] CNN clique-{n} K={K}: plain {plain_ms:.1f} "
          f"ms/round, per_slot {per_slot_ms and round(per_slot_ms, 1)}, "
          f"multi {multi_ms and round(multi_ms, 1)}; deliver-phase ms "
          f"{deliver_ms}" + (f" (error: {err})" if err else ""),
          file=sys.stderr)
    speedup = (plain_ms / multi_ms) if multi_ms else None
    emit({
        "metric": "fused_merge_speedup_cnn_clique",
        "value": round(speedup, 3) if speedup else None,
        "unit": "x_vs_xla_gather_blend",
        "vs_baseline": round(speedup, 3) if speedup else None,
        "raw": {
            **PERF_INFO,
            "plain_ms_per_round": round(plain_ms, 2),
            "fused_ms_per_round": (round(multi_ms, 2)
                                   if multi_ms is not None else None),
            "per_slot_ms_per_round": (round(per_slot_ms, 2)
                                      if per_slot_ms is not None else None),
            "deliver_ms_per_round": deliver_ms,
            "deliver_timing_mode": ("tpu" if jax.default_backend() == "tpu"
                                    else "cpu_interpreter"),
            "deliver_config": {**deliver_config, "rounds": d_rounds,
                               "mailbox_slots": K},
            "deliver_bytes_moved": deliver_bytes,
            "mailbox_slots": K,
            "n_nodes": n, "topology": "clique", "rounds": rounds,
            "error": err,
        },
    })


def _backend_alive() -> bool:
    """Shared disposable-child probe (``_virtual_mesh.probe_backend_alive``):
    a wedged TPU tunnel hangs backend init indefinitely, and benching must
    never hang the driver. Returns False on hang or child failure so the
    caller can degrade to a labeled CPU run instead of exiting 1."""
    import _virtual_mesh
    ok, detail = _virtual_mesh.probe_backend_alive()
    if not ok:
        print(f"[bench] accelerator backend unreachable: {detail}",
              file=sys.stderr)
    return ok


def _poll_budget(deadline: float) -> float:
    """Seconds the pre-watchdog probe poll may spend: the
    ``GOSSIPY_TPU_BENCH_PROBE_POLL`` override if set (0 disables polling —
    the evidence script's setting, whose OUTER loop already polls), else
    half the (already override-resolved) watchdog deadline. Shared by the
    poll itself and ``--print-deadline`` so the outer-timeout contract
    (``print-deadline + fixed headroom``) covers the poll too."""
    import math
    raw = os.environ.get("GOSSIPY_TPU_BENCH_PROBE_POLL", "")
    try:
        val = float(raw) if raw else deadline / 2.0
        # nan parses fine but would poll forever (nan <= 0 is False every
        # iteration); inf would crash --print-deadline's int().
        if not math.isfinite(val) or val < 0:
            raise ValueError(raw)
        return val
    except ValueError:
        print("[bench] ignoring malformed GOSSIPY_TPU_BENCH_PROBE_POLL="
              f"{raw!r}; using deadline/2", file=sys.stderr)
        return deadline / 2.0


def _backend_alive_with_poll(deadline: float) -> bool:
    """Probe the backend, then keep polling for up to ``_poll_budget``
    before giving up (VERDICT r3 #4: the driver-visible bench row should be
    a TPU row whenever ANY window opens during its run — the tunnel has
    repeatedly come back minutes after a wedge). ``deadline`` must already
    be override-resolved. Each hung probe burns its own 150 s child
    timeout, which counts against the budget.
    """
    budget = _poll_budget(deadline)
    start = time.monotonic()
    if _backend_alive():
        return True
    attempt = 1
    while True:
        remaining = budget - (time.monotonic() - start)
        if remaining <= 0:
            if budget > 0:
                print("[bench] backend still unreachable after "
                      f"{budget:.0f}s of polling ({attempt} probes) — "
                      "degrading", file=sys.stderr)
            return False
        time.sleep(min(45.0, remaining))
        attempt += 1
        print(f"[bench] probe retry {attempt} "
              f"({remaining:.0f}s of poll budget left)", file=sys.stderr)
        if _backend_alive():
            return True


def _deadline_override(default: float) -> float:
    """The watchdog deadline: ``GOSSIPY_TPU_BENCH_DEADLINE`` if set and
    parsable, else ``default``. The ONE place the override is interpreted —
    both the watchdog and ``--print-deadline`` (which the evidence script's
    outer timeout is derived from) go through here, so they cannot drift.
    """
    raw = os.environ.get("GOSSIPY_TPU_BENCH_DEADLINE", "")
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        print("[bench] ignoring malformed GOSSIPY_TPU_BENCH_DEADLINE="
              f"{raw!r}; using {default:.0f}", file=sys.stderr)
        return default


def _run_with_watchdog(deadline: float = 1500.0) -> None:
    """Run the accelerator attempt in a deadline-guarded child.

    A live probe does not guarantee a live run: the tunneled runtime has
    been observed to initialize fine in the probe subprocess and then wedge
    the very next client mid-initialization or mid-execution (2026-07-31:
    main thread asleep at ~1% CPU, axon relay thread parked on epoll,
    indefinitely). The child's stdout is streamed through line by line
    (unbuffered child, so the JSON row crosses the pipe the moment it is
    printed); if the child does not finish inside the deadline it is killed
    and the bench degrades to the labeled CPU fallback — the driver gets a
    parseable row in every tunnel state, including mid-run wedges.

    Two deliberate asymmetries: a child that already emitted its JSON row
    and THEN wedged or crashed (e.g. in jax runtime teardown) is treated as
    success — the accelerator measurement is out and must not be superseded
    by a degraded CPU row; and a degrade triggered by a nonzero child exit
    is labeled with that rc in the row (``raw.degrade_reason``) so a
    deterministic bench/engine crash stays distinguishable from a tunnel
    outage (the child's traceback also passes through on stderr).
    The deadline is mode-aware (resolved by the caller): the driver's
    north-star run gets 1500 s (measured healthy time ≈ 3-4 min including a
    cold compile), while big ``--scale N`` rows grow with N — the repo's own
    records put 500k nodes at 0.10 r/s, i.e. ~2000 s of legitimate runtime
    for the two 100-round passes, which a flat deadline would kill and
    mislabel as a wedge. Override: ``GOSSIPY_TPU_BENCH_DEADLINE`` (seconds).
    ``scripts/run_tpu_evidence.sh`` sizes its outer per-mode timeout as
    probe + this deadline + CPU-fallback headroom so a mid-run wedge still
    ends inside the budget with a labeled row.
    """
    import subprocess
    import threading
    # ``deadline`` arrives already resolved through _deadline_override in
    # main() — re-applying it here would print the malformed-env warning
    # twice (round-4 advisor).
    import signal
    env = dict(os.environ, PYTHONUNBUFFERED="1")
    # Own session: if THIS process is killed externally (e.g. the evidence
    # script's outer timeout), the finally below still reaps the — possibly
    # wedged, tunnel-holding — child by process group instead of orphaning
    # it into every subsequent mode.
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), *sys.argv[1:],
         "--_accel-inner"], env=env, stdout=subprocess.PIPE, text=True,
        start_new_session=True)
    emitted = []

    def pump():
        for line in proc.stdout:
            sys.stdout.write(line)
            sys.stdout.flush()
            if line.startswith("{"):
                emitted.append(line)

    t = threading.Thread(target=pump, daemon=True)
    t.start()
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(143))
    rc = None
    start = time.monotonic()
    emit_seen_at = None
    grace_after_emit = 60.0
    try:
        while True:
            try:
                # Poll granularity must not exceed the deadline itself, or
                # sub-5s deadlines (the wedge test) silently become ~5s.
                rc = proc.wait(timeout=min(5.0, deadline))
                break
            except subprocess.TimeoutExpired:
                now = time.monotonic()
                if emitted and emit_seen_at is None:
                    emit_seen_at = now
                # Once the one JSON row is out, don't idle away the rest of
                # the deadline on a wedged teardown — a short grace, then
                # reap and keep the measurement.
                if (emit_seen_at is not None
                        and now - emit_seen_at > grace_after_emit):
                    break
                if now - start > deadline:
                    break
    finally:
        if proc.poll() is None:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            proc.wait()
        t.join(timeout=10)
    if rc is None:  # wedged: killed above after grace/deadline expiry
        if emitted:
            print("[bench] accelerator run emitted its row but wedged "
                  "before exiting — keeping the measurement", file=sys.stderr)
            sys.exit(0)
        print("[bench] accelerator run wedged: no result after "
              f"{deadline:.0f}s (probe had succeeded) — killed it, "
              "degrading", file=sys.stderr)
        _degrade_to_cpu("wedged_after_probe")  # does not return
    if rc != 0:
        if emitted:
            print("[bench] accelerator run emitted its row but exited "
                  f"rc={rc} (teardown failure) — keeping the measurement",
                  file=sys.stderr)
            sys.exit(0)
        print(f"[bench] accelerator run failed (rc={rc}) — degrading",
              file=sys.stderr)
        _degrade_to_cpu(f"accel_run_rc_{rc}")  # does not return
    sys.exit(0)


def _degrade_to_cpu(reason: str = "backend_unreachable") -> None:
    """Re-exec this bench in a cleaned CPU-only environment.

    The child strips the TPU-plugin sitecustomize from PYTHONPATH (so
    ``import jax`` cannot hang on the dead tunnel) and runs the same mode
    with ``--_degraded``, which stamps ``"backend": "cpu",
    "degraded": true`` plus ``degrade_reason`` into the JSON line — an
    outage round records a labeled data point instead of rc=1, and a
    crash-triggered degrade stays distinguishable from a tunnel outage.
    """
    import subprocess
    import _virtual_mesh
    here = os.path.dirname(os.path.abspath(__file__))
    env = _virtual_mesh.virtual_mesh_env(1, extra_path=here)
    env["GOSSIPY_TPU_DEGRADE_REASON"] = reason
    print(f"[bench] degrading to a labeled CPU fallback run ({reason})",
          file=sys.stderr)
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), *sys.argv[1:],
         "--_degraded"], env=env, cwd=here)
    sys.exit(proc.returncode)


def _mode_arg(flag: str, default: int, minimum: int) -> int:
    """Integer argument following ``flag``; ``default`` when absent.

    A present-but-unparsable or out-of-range value is a hard usage error —
    silently substituting the default would produce a differently-scoped
    measurement on a typo.
    """
    i = sys.argv.index(flag)
    arg = sys.argv[i + 1] if len(sys.argv) > i + 1 else ""
    if arg == "" or arg.startswith("--"):
        return default
    try:
        val = int(arg)
    except ValueError:
        sys.exit(f"usage: python bench.py {flag} <int >= {minimum}>; "
                 f"got {arg!r}")
    if val < minimum:
        sys.exit(f"usage: python bench.py {flag} <int >= {minimum}>; "
                 f"got {val}")
    return val


def bench_service_slo(n_tenants: int) -> None:
    """Sustained mixed-shape arrival benchmark (``--service-slo [T]``):
    T tenants arrive as a compressed Poisson process over the built-in
    two-shape spec pool and are served open-loop by the incremental
    multi-tenant scheduler (gossipy_tpu.service.slo). The row is the
    ROADMAP always-on-service item's "Done" evidence: realized
    tenants/hour plus p50/p99 time-to-first-round and p99 per-round
    latency, with every admitted tenant's TTFR accounted for. Emitted
    through :func:`emit` so the backend/degraded stamps ride along."""
    import shutil
    import tempfile

    from gossipy_tpu.service.slo import run_load
    from gossipy_tpu.telemetry.metrics import MetricsRegistry, set_registry

    out = tempfile.mkdtemp(prefix="bench-slo-")
    reg = MetricsRegistry()
    prev = set_registry(reg)
    try:
        result = run_load(out, n_tenants=n_tenants, rate_per_hour=1200.0,
                          seed=0, slice_rounds=3, registry=reg,
                          time_scale=0.001)
    finally:
        set_registry(prev)
        shutil.rmtree(out, ignore_errors=True)
    row = result["row"]
    raw = row["raw"]
    print(f"[bench] service-slo: {raw['n_admitted']} tenants in "
          f"{raw['wall_seconds']}s -> {row['value']} tenants/hour, "
          f"ttfr p99 {raw['ttfr_p99_ms']} ms, "
          f"round p99 {raw['round_p99_ms']} ms", file=sys.stderr)
    emit(row)


_USAGE = """usage: python bench.py [MODE]

Driver contract: prints ONE JSON line; degrades to a labeled CPU fallback
when the accelerator is unreachable or wedges mid-run.

modes (default: the 100-node north-star, ours vs the live reference):
  --mfu [ROUNDS]            CNN-config MFU vs the chip's bf16 peak
  --mfu-wide [ROUNDS]       same, compact_deliver off (full-width masked
                            slot passes): the on-chip A/B control for the
                            round-5 compaction
  --mfu-reps [S]            S seed-batched simulations in one vmapped
                            program (50 rounds each): the MXU-filling
                            throughput row
  --mfu-all2all [ROUNDS]    same workload under the All2All protocol (the
                            one-einsum merge: the engine's MFU upper end)
  --scale [N]               N-node rounds/s over a CSR SparseTopology
  --scale-all2all [N]       Koloskova variant at N nodes, sparse mixing
  --cohort [N]              active-cohort rounds/s at NOMINAL N (default
                            1M): resident pool + sampled [C]-wide rounds
                            (simulation.cohort); C via
                            GOSSIPY_TPU_COHORT_SIZE (default 1024); raw
                            carries pool_bytes vs active_bytes vs the
                            materialized prediction
  --fused-regime [ROUNDS]   pallas fused merge vs XLA gather+blend
  --ring-attn [S]           flash-attention kernel vs XLA dense attention
  --to-acc TARGET           wall-clock to reach TARGET global accuracy
  --service-slo [T]         sustained mixed-shape arrival benchmark: T
                            Poisson-arriving tenants served open-loop by
                            the multi-tenant scheduler; the row carries
                            tenants/hour, p50/p99 time-to-first-round and
                            p99 round latency (scripts/loadgen.py is the
                            standalone driver)
  --print-deadline [MODE]   print the mode's watchdog deadline and exit

options (compose with any mode):
  --history-dtype FMT       params-history ring wire format: float32
                            (default, exact), bfloat16, int8 — the
                            quantized ring cuts history_ring_bytes and the
                            deliver phase's HBM gather traffic 2-4x; the
                            run stamps bytes-moved-per-round on stderr and
                            in the JSON raw block

env: GOSSIPY_TPU_BENCH_DEADLINE overrides the watchdog deadline (seconds).
     GOSSIPY_TPU_COMPILATION_CACHE=1|<dir> persists XLA compilations.
"""


def main():
    global DEGRADED
    if "-h" in sys.argv or "--help" in sys.argv:
        try:
            print(_USAGE)
        except BrokenPipeError:  # `bench.py --help | head` closes early
            pass
        return
    if "--_degraded" in sys.argv:
        DEGRADED = True
        sys.argv.remove("--_degraded")
    inner = "--_accel-inner" in sys.argv
    if inner:
        sys.argv.remove("--_accel-inner")

    # Parse argv first: usage errors must not pay the backend probe.
    # --history-dtype composes with every mode (it is NOT removed from
    # sys.argv: the watchdog/degrade paths re-exec with sys.argv[1:] and
    # must propagate it to the child).
    global HISTORY_DTYPE
    if "--history-dtype" in sys.argv:
        i = sys.argv.index("--history-dtype")
        val = sys.argv[i + 1] if len(sys.argv) > i + 1 else ""
        if val not in ("float32", "bfloat16", "int8"):
            sys.exit("usage: python bench.py [MODE] --history-dtype "
                     f"{{float32,bfloat16,int8}}; got {val!r}")
        HISTORY_DTYPE = val
    mode, mode_arg = "north-star", None
    if "--mfu-all2all" in sys.argv:
        mode, mode_arg = "mfu-all2all", _mode_arg("--mfu-all2all",
                                                  default=50, minimum=1)
    elif "--mfu-wide" in sys.argv:
        mode, mode_arg = "mfu-wide", _mode_arg("--mfu-wide",
                                               default=50, minimum=1)
    elif "--mfu-reps" in sys.argv:
        mode, mode_arg = "mfu-reps", _mode_arg("--mfu-reps",
                                               default=8, minimum=1)
    elif "--mfu" in sys.argv:
        mode, mode_arg = "mfu", _mode_arg("--mfu", default=50, minimum=1)
    elif "--scale-all2all" in sys.argv:
        mode, mode_arg = "scale-all2all", _mode_arg(
            "--scale-all2all", default=50_000, minimum=2)
    elif "--scale" in sys.argv:
        mode, mode_arg = "scale", _mode_arg("--scale", default=50_000,
                                            minimum=2)
    elif "--cohort" in sys.argv:
        mode, mode_arg = "cohort", _mode_arg("--cohort",
                                             default=1_000_000, minimum=2)
    elif "--fused-regime" in sys.argv:
        mode, mode_arg = "fused", _mode_arg("--fused-regime", default=40,
                                            minimum=1)
    elif "--ring-attn" in sys.argv:
        mode, mode_arg = "ring-attn", _mode_arg("--ring-attn", default=8192,
                                                minimum=16)
    elif "--service-slo" in sys.argv:
        mode, mode_arg = "service-slo", _mode_arg("--service-slo",
                                                  default=6, minimum=1)
    elif "--to-acc" in sys.argv:
        try:
            mode_arg = float(sys.argv[sys.argv.index("--to-acc") + 1])
        except (IndexError, ValueError):
            sys.exit("usage: python bench.py --to-acc <target accuracy in "
                     "(0, 1]>, e.g. --to-acc 0.95")
        mode = "to-acc"

    if mode in ("scale", "scale-all2all"):
        # Two 100-round passes over N nodes: scale the budget with N
        # (500k nodes measured at 0.10 r/s -> ~2000s of healthy work).
        deadline = 1500.0 + 0.025 * mode_arg
    elif mode == "cohort":
        # Rounds are C-wide (cheap); only the pool init/gathers scale
        # with nominal N, and linearly at small constant.
        deadline = 1500.0 + 2.5e-4 * mode_arg
    elif mode == "fused":
        deadline = 2400.0  # two full CNN-clique compiles + 2x2 passes
    elif mode in ("mfu", "mfu-wide", "mfu-reps", "mfu-all2all"):
        deadline = 2400.0  # up to 3 CNN compiles (FLOP decomposition + timed)
    else:
        deadline = 1500.0
    deadline = _deadline_override(deadline)
    if "--print-deadline" in sys.argv:
        # Budget query for scripts/run_tpu_evidence.sh: the mode-aware
        # watchdog deadline lives in ONE place (here); the script derives
        # its outer timeout from this instead of re-encoding the formula.
        # Includes the probe-poll budget so a run that spends its whole
        # poll AND its whole deadline still fits the derived outer timeout.
        # Must not touch jax: answers even while the tunnel is wedged.
        print(int(deadline + _poll_budget(deadline)))
        return
    if not DEGRADED and not inner:
        if not _backend_alive_with_poll(deadline):
            _degrade_to_cpu()  # does not return
        _run_with_watchdog(deadline)  # does not return
    from gossipy_tpu import enable_compilation_cache
    enable_compilation_cache()
    if mode == "mfu":
        bench_mfu(mode_arg)
        return
    if mode == "mfu-wide":
        bench_mfu(mode_arg, compact=False)
        return
    if mode == "mfu-reps":
        bench_mfu(50, reps=mode_arg)
        return
    if mode == "mfu-all2all":
        bench_mfu(mode_arg, variant="all2all")
        return
    if mode == "scale":
        bench_scale(mode_arg)
        return
    if mode == "cohort":
        bench_cohort(mode_arg)
        return
    if mode == "scale-all2all":
        bench_scale_all2all(mode_arg)
        return
    if mode == "fused":
        bench_fused_regime(mode_arg)
        return
    if mode == "ring-attn":
        bench_ring_attention(mode_arg)
        return
    if mode == "service-slo":
        bench_service_slo(mode_arg)
        return
    X, y = make_data()
    if mode == "to-acc":
        bench_to_accuracy(X, y, mode_arg)
        return
    ours = bench_ours(X, y)
    baseline_source = "live"
    try:
        baseline = bench_reference(X, y)
    except Exception as e:  # environmental failure only
        print(f"[bench] reference baseline failed ({e!r}); "
              f"using fallback {FALLBACK_BASELINE} r/s", file=sys.stderr)
        baseline = FALLBACK_BASELINE
        baseline_source = "fallback"
    ref_rounds = (BASELINE_ROUNDS if baseline_source == "live"
                  else FALLBACK_BASELINE_ROUNDS)
    emit({
        "metric": "sim_rounds_per_sec_100nodes",
        "value": round(ours, 2),
        "unit": "rounds/s",
        "vs_baseline": round(ours / baseline, 2),
        "raw": {
            **WIRE_INFO,
            **PROBE_INFO,
            **SENTINEL_INFO,
            **CHAOS_INFO,
            **PERF_INFO,
            **TRACING_INFO,
            "ours_rounds_per_sec": round(ours, 2),
            "ours_rounds_measured": (BENCH_ROUNDS_DEGRADED if DEGRADED
                                     else BENCH_ROUNDS),
            "reference_rounds_per_sec": round(baseline, 3),
            "reference_rounds_measured": ref_rounds,
            "baseline_source": baseline_source,
            "baseline_note": "reference measured live on this host's CPU "
                             "(the reference has no accelerator path for "
                             "this workload)",
        },
    })


if __name__ == "__main__":
    main()
