"""Virtual-mesh environment provisioning (shared, jax-free).

Multi-chip hardware is not attached in CI or under the driver; sharded code
paths are proven on ``--xla_force_host_platform_device_count=N`` CPU devices —
the same XLA partitioner and collectives as a real mesh. This module builds
the child-process environment for that and is imported by both
``tests/conftest.py`` (pytest re-exec) and ``__graft_entry__.py`` (driver
dryrun subprocess). It must stay import-safe before jax initializes.
"""

from __future__ import annotations

import os

#: Virtual device count used by the test suite's CPU mesh.
TEST_DEVICE_COUNT = 8


_FLAG_NAME = "--xla_force_host_platform_device_count"


def host_device_flag(n_devices: int) -> str:
    """The XLA flag forcing ``n_devices`` virtual CPU devices."""
    return f"{_FLAG_NAME}={n_devices}"


def provisioned_device_count(xla_flags: str) -> int | None:
    """The virtual device count an ``XLA_FLAGS`` string provisions, if any.

    Exact token parse (last occurrence wins, matching absl's duplicate-flag
    resolution) — a substring test would false-match e.g. ``=80`` against
    ``=8``.
    """
    count = None
    for tok in xla_flags.split():
        name, sep, val = tok.partition("=")
        if name == _FLAG_NAME and sep:
            try:
                count = int(val)
            except ValueError:
                pass
    return count


def probe_backend_alive(timeout: float = 150.0) -> tuple[bool, str]:
    """Probe in a disposable child that the default jax backend initializes.

    A wedged TPU tunnel hangs backend init indefinitely; every driver-facing
    entry point (``bench.py``, ``__graft_entry__``) must detect that in a
    killable child instead of hanging in-process. Returns ``(ok, detail)``
    where ``detail`` is the failure description (timeout note or the
    child's trailing stderr) — the ONE shared probe, so timeout policy and
    error surfacing cannot diverge between entry points.
    """
    import subprocess
    import sys

    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout, capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        return False, (f"jax backend init still hung after {timeout:.0f}s "
                       "in a probe subprocess")
    if proc.returncode != 0:
        return False, ("jax backend failed to initialize in the probe "
                       f"subprocess (rc={proc.returncode}); child stderr:\n"
                       + proc.stderr[-2000:])
    return True, ""


def _is_tpu_plugin_entry(path: str) -> bool:
    """True for PYTHONPATH entries that belong to the TPU-plugin sitecustomize.

    The axon plugin registers a TPU backend at interpreter startup via a
    sitecustomize hook (e.g. ``/root/.axon_site``). Match the path *component*
    (not a bare substring) so unrelated paths that merely contain "axon"
    survive.
    """
    return any(comp.startswith(".axon") or comp == "axon_site"
               for comp in path.split(os.sep))


def virtual_mesh_env(n_devices: int, base_env: dict | None = None,
                     extra_path: str | None = None) -> dict:
    """Environment for a child interpreter with ``n_devices`` virtual CPU devices.

    Sets ``JAX_PLATFORMS=cpu``, appends the host-platform device-count flag to
    ``XLA_FLAGS`` (appended last so it wins duplicate-flag resolution), and
    strips TPU-plugin sitecustomize entries from PYTHONPATH so the child
    starts clean on CPU. ``extra_path`` (e.g. the repo root) is prepended.
    """
    env = dict(os.environ if base_env is None else base_env)
    env["JAX_PLATFORMS"] = "cpu"
    flag = host_device_flag(n_devices)
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") + " " + flag).strip()
    entries = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
               if p and not _is_tpu_plugin_entry(p)]
    if extra_path:
        entries.insert(0, extra_path)
    env["PYTHONPATH"] = os.pathsep.join(entries)
    return env
